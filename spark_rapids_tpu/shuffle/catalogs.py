"""Shuffle buffer catalogs: device-resident map output + received buffers.

Reference analog (SURVEY.md §2f): ``ShuffleBufferCatalog.scala:50-232``
(shuffleId -> bufferIds mapping over RapidsBufferCatalog, so cached map
output stays spillable in the device store) and
``ShuffleReceivedBufferCatalog.scala:119`` with ``TempSpillBufferId``
(:49) for reducer-side received buffers.

Batches are held as ``SpillableBatch`` handles in the global spill
catalog (mem/spill.py), so shuffle data competes with operator data for
HBM under the same priority-ordered spill policy
(INPUT_FROM_SHUFFLE_PRIORITY — spills first).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import pyarrow as pa

from spark_rapids_tpu.columnar.batch import DeviceBatch, to_arrow
from spark_rapids_tpu.mem import spill
from spark_rapids_tpu.shuffle import meta as wire
from spark_rapids_tpu.shuffle.serializer import (deserialize_table,
                                                 get_codec, serialize_table)


def _dtype_code(d) -> str:
    return getattr(d, "code", str(d))


_ARROW_TYPE_CODES = {
    "timestamp[us]": pa.timestamp("us"),
    "timestamp[us, tz=UTC]": pa.timestamp("us", tz="UTC"),
    "date32[day]": pa.date32(),
    "large_string": pa.large_string(),
}


def _parse_arrow_type(code: str) -> pa.DataType:
    """Inverse of ``str(pa.DataType)`` for the types the engine supports
    (GpuColumnVector.java:153-197 type-map analog)."""
    if code in _ARROW_TYPE_CODES:
        return _ARROW_TYPE_CODES[code]
    try:
        return pa.type_for_alias(code)
    except ValueError:
        if code.startswith("list<item: ") and code.endswith(">"):
            return pa.list_(_parse_arrow_type(code[len("list<item: "):-1]))
        raise ValueError(f"unsupported wire dtype {code!r}")


def build_table_meta(buffer_id: int, batch_rows: int,
                     table: pa.Table, payload_size: int,
                     codec: int = wire.CODEC_UNCOMPRESSED,
                     uncompressed_size: Optional[int] = None
                     ) -> wire.TableMeta:
    """MetaUtils.buildTableMeta analog (MetaUtils.scala:48)."""
    cols = [wire.ColumnMeta(f.name, str(f.type), f.nullable,
                            table.column(i).null_count)
            for i, f in enumerate(table.schema)]
    bm = wire.BufferMeta(buffer_id, uncompressed_size or payload_size,
                         payload_size, codec)
    return wire.TableMeta(batch_rows, cols, bm)


def build_degenerate_table_meta(table: pa.Table) -> wire.TableMeta:
    """0-row / 0-col batches ship as metadata only
    (MetaUtils.buildDegenerateTableMeta MetaUtils.scala:145)."""
    cols = [wire.ColumnMeta(f.name, str(f.type), f.nullable, 0)
            for f in table.schema]
    return wire.TableMeta(table.num_rows, cols, None)


@dataclass
class ShuffleBlock:
    """One map-output slice for one reduce partition."""
    buffer_id: int
    shuffle_id: int
    map_id: int
    reduce_id: int
    table_meta: wire.TableMeta
    spillable: Optional[spill.SpillableBatch]   # device-resident path
    host_table: Optional[pa.Table]              # degenerate / host fallback
    payload: Optional[bytes] = None             # cached wire bytes


class ShuffleBufferCatalog:
    """Mapper-side: shuffle block registry over the spill catalog."""

    def __init__(self, codec_name: str = "none"):
        self._ids = itertools.count(1)
        self._blocks: Dict[int, ShuffleBlock] = {}
        self._by_shuffle: Dict[int, List[int]] = {}
        self._lock = threading.Lock()
        self.codec_name = codec_name

    def register_batch(self, shuffle_id: int, map_id: int, reduce_id: int,
                       batch: DeviceBatch) -> ShuffleBlock:
        """RapidsCachingWriter.write analog
        (RapidsShuffleInternalManager.scala:90-155): the batch stays in the
        device store, registered spillable at shuffle priority."""
        table = to_arrow(batch)
        bid = next(self._ids)
        if table.num_rows == 0 or table.num_columns == 0:
            tm = build_degenerate_table_meta(table)
            blk = ShuffleBlock(bid, shuffle_id, map_id, reduce_id, tm,
                               None, table)
        else:
            # the wire payload is serialized once here and cached; remote
            # fetches reuse it instead of re-encoding per request
            payload = self.serialize_block_table(table)
            tm = build_table_meta(bid, table.num_rows, table, len(payload),
                                  wire.codec_id(self.codec_name)
                                  if self.codec_name != "none"
                                  else wire.CODEC_UNCOMPRESSED)
            sp = None
            if spill.is_enabled():
                sp = spill.get_catalog().register(
                    batch, priority=spill.INPUT_FROM_SHUFFLE_PRIORITY)
                blk = ShuffleBlock(bid, shuffle_id, map_id, reduce_id, tm,
                                   sp, None, payload)
            else:
                blk = ShuffleBlock(bid, shuffle_id, map_id, reduce_id, tm,
                                   None, table, payload)
        with self._lock:
            self._blocks[bid] = blk
            self._by_shuffle.setdefault(shuffle_id, []).append(bid)
        return blk

    def serialize_block_table(self, table: pa.Table) -> bytes:
        return serialize_table(table, get_codec(self.codec_name))

    def blocks_for(self, shuffle_id: int, reduce_id: int,
                   map_ids: Optional[List[int]] = None) -> List[ShuffleBlock]:
        with self._lock:
            ids = self._by_shuffle.get(shuffle_id, [])
            out = []
            for bid in ids:
                b = self._blocks[bid]
                if b.reduce_id != reduce_id:
                    continue
                if map_ids and b.map_id not in map_ids:
                    continue
                out.append(b)
            return out

    def get_block(self, buffer_id: int) -> ShuffleBlock:
        with self._lock:
            return self._blocks[buffer_id]

    def block_payload(self, buffer_id: int) -> bytes:
        """Wire payload for a block: the cached bytes from registration,
        or re-encoded from the (possibly unspilled) batch."""
        blk = self.get_block(buffer_id)
        if blk.payload is not None:
            return blk.payload
        if blk.host_table is not None:
            return self.serialize_block_table(blk.host_table)
        batch = blk.spillable.get()
        return self.serialize_block_table(to_arrow(batch))

    def unregister_shuffle(self, shuffle_id: int) -> None:
        """ShuffleManager.unregisterShuffle analog — frees device store."""
        with self._lock:
            ids = self._by_shuffle.pop(shuffle_id, [])
            blocks = [self._blocks.pop(b) for b in ids if b in self._blocks]
        for b in blocks:
            if b.spillable is not None:
                b.spillable.close()


@dataclass
class ReceivedBuffer:
    temp_id: int
    table_meta: wire.TableMeta
    data: bytes


class ShuffleReceivedBufferCatalog:
    """Reducer-side catalog of fetched buffers awaiting materialization
    (ShuffleReceivedBufferCatalog.scala:119; temp ids TempSpillBufferId
    :49)."""

    def __init__(self):
        self._ids = itertools.count(1)
        self._received: Dict[int, ReceivedBuffer] = {}
        self._lock = threading.Lock()

    def add(self, table_meta: wire.TableMeta, data: bytes) -> int:
        with self._lock:
            tid = next(self._ids)
            self._received[tid] = ReceivedBuffer(tid, table_meta, data)
            return tid

    def materialize(self, temp_id: int) -> pa.Table:
        """Decode the received payload into a host table and drop it.
        Degenerate blocks (no payload) are rebuilt from metadata alone,
        as the reference does (MetaUtils.scala:145)."""
        with self._lock:
            rb = self._received.pop(temp_id)
        if rb.table_meta.is_degenerate:
            if not rb.table_meta.columns and rb.table_meta.num_rows:
                # pyarrow cannot represent a zero-column table with rows;
                # fail loudly rather than silently dropping the row count
                raise NotImplementedError(
                    f"zero-column block with {rb.table_meta.num_rows} rows "
                    "cannot be materialized as a pyarrow table")
            fields = [pa.field(c.name, _parse_arrow_type(c.dtype_code),
                               c.nullable)
                      for c in rb.table_meta.columns]
            schema = pa.schema(fields)
            return pa.table(
                {f.name: pa.array([], type=f.type) for f in fields},
                schema=schema)
        return deserialize_table(rb.data)

    def free(self, temp_id: int) -> None:
        """Drop a received buffer without materializing it — the
        iterator's error path releases undelivered fetches so an aborted
        read doesn't leak catalog entries."""
        with self._lock:
            self._received.pop(temp_id, None)

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._received)
