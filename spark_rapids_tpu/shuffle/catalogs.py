"""Shuffle buffer catalogs: device-resident map output + received buffers.

Reference analog (SURVEY.md §2f): ``ShuffleBufferCatalog.scala:50-232``
(shuffleId -> bufferIds mapping over RapidsBufferCatalog, so cached map
output stays spillable in the device store) and
``ShuffleReceivedBufferCatalog.scala:119`` with ``TempSpillBufferId``
(:49) for reducer-side received buffers.

Batches are held as ``SpillableBatch`` handles in the global spill
catalog (mem/spill.py), so shuffle data competes with operator data for
HBM under the same priority-ordered spill policy
(INPUT_FROM_SHUFFLE_PRIORITY — spills first).
"""

from __future__ import annotations

import itertools
import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import pyarrow as pa

from spark_rapids_tpu.columnar.batch import DeviceBatch, to_arrow
from spark_rapids_tpu.mem import spill
from spark_rapids_tpu.shuffle import meta as wire
from spark_rapids_tpu.shuffle.serializer import (deserialize_table,
                                                 get_codec, serialize_table)


def _dtype_code(d) -> str:
    return getattr(d, "code", str(d))


_ARROW_TYPE_CODES = {
    "timestamp[us]": pa.timestamp("us"),
    "timestamp[us, tz=UTC]": pa.timestamp("us", tz="UTC"),
    "date32[day]": pa.date32(),
    "large_string": pa.large_string(),
}


def _parse_arrow_type(code: str) -> pa.DataType:
    """Inverse of ``str(pa.DataType)`` for the types the engine supports
    (GpuColumnVector.java:153-197 type-map analog)."""
    if code in _ARROW_TYPE_CODES:
        return _ARROW_TYPE_CODES[code]
    try:
        return pa.type_for_alias(code)
    except ValueError:
        if code.startswith("list<item: ") and code.endswith(">"):
            return pa.list_(_parse_arrow_type(code[len("list<item: "):-1]))
        raise ValueError(f"unsupported wire dtype {code!r}")


def build_table_meta(buffer_id: int, batch_rows: int,
                     table: pa.Table, payload_size: int,
                     codec: int = wire.CODEC_UNCOMPRESSED,
                     uncompressed_size: Optional[int] = None
                     ) -> wire.TableMeta:
    """MetaUtils.buildTableMeta analog (MetaUtils.scala:48)."""
    cols = [wire.ColumnMeta(f.name, str(f.type), f.nullable,
                            table.column(i).null_count)
            for i, f in enumerate(table.schema)]
    bm = wire.BufferMeta(buffer_id, uncompressed_size or payload_size,
                         payload_size, codec)
    return wire.TableMeta(batch_rows, cols, bm)


def build_degenerate_table_meta(table: pa.Table) -> wire.TableMeta:
    """0-row / 0-col batches ship as metadata only
    (MetaUtils.buildDegenerateTableMeta MetaUtils.scala:145)."""
    cols = [wire.ColumnMeta(f.name, str(f.type), f.nullable, 0)
            for f in table.schema]
    return wire.TableMeta(table.num_rows, cols, None)


@dataclass
class ShuffleBlock:
    """One map-output slice for one reduce partition."""
    buffer_id: int
    shuffle_id: int
    map_id: int
    reduce_id: int
    table_meta: wire.TableMeta
    spillable: Optional[spill.SpillableBatch]   # device-resident path
    host_table: Optional[pa.Table]              # degenerate / host fallback
    payload: Optional[bytes] = None             # cached wire bytes


class ShuffleBufferCatalog:
    """Mapper-side: shuffle block registry over the spill catalog."""

    def __init__(self, codec_name: str = "none"):
        self._ids = itertools.count(1)
        self._blocks: Dict[int, ShuffleBlock] = {}
        self._by_shuffle: Dict[int, List[int]] = {}
        self._lock = threading.Lock()
        self.codec_name = codec_name

    def register_batch(self, shuffle_id: int, map_id: int, reduce_id: int,
                       batch: DeviceBatch) -> ShuffleBlock:
        """RapidsCachingWriter.write analog
        (RapidsShuffleInternalManager.scala:90-155): the batch stays in the
        device store, registered spillable at shuffle priority."""
        table = to_arrow(batch)
        bid = next(self._ids)
        if table.num_rows == 0 or table.num_columns == 0:
            tm = build_degenerate_table_meta(table)
            blk = ShuffleBlock(bid, shuffle_id, map_id, reduce_id, tm,
                               None, table)
        else:
            # the wire payload is serialized once here and cached; remote
            # fetches reuse it instead of re-encoding per request
            payload = self.serialize_block_table(table)
            tm = build_table_meta(bid, table.num_rows, table, len(payload),
                                  wire.codec_id(self.codec_name)
                                  if self.codec_name != "none"
                                  else wire.CODEC_UNCOMPRESSED)
            sp = None
            if spill.is_enabled():
                sp = spill.get_catalog().register(
                    batch, priority=spill.INPUT_FROM_SHUFFLE_PRIORITY)
                blk = ShuffleBlock(bid, shuffle_id, map_id, reduce_id, tm,
                                   sp, None, payload)
            else:
                blk = ShuffleBlock(bid, shuffle_id, map_id, reduce_id, tm,
                                   None, table, payload)
        with self._lock:
            self._blocks[bid] = blk
            self._by_shuffle.setdefault(shuffle_id, []).append(bid)
        return blk

    def serialize_block_table(self, table: pa.Table) -> bytes:
        return serialize_table(table, get_codec(self.codec_name))

    def blocks_for(self, shuffle_id: int, reduce_id: int,
                   map_ids: Optional[List[int]] = None) -> List[ShuffleBlock]:
        with self._lock:
            ids = self._by_shuffle.get(shuffle_id, [])
            out = []
            for bid in ids:
                b = self._blocks[bid]
                if b.reduce_id != reduce_id:
                    continue
                if map_ids and b.map_id not in map_ids:
                    continue
                out.append(b)
            return out

    def get_block(self, buffer_id: int) -> ShuffleBlock:
        with self._lock:
            return self._blocks[buffer_id]

    def block_payload(self, buffer_id: int) -> bytes:
        """Wire payload for a block: the cached bytes from registration,
        or re-encoded from the (possibly unspilled) batch."""
        blk = self.get_block(buffer_id)
        if blk.payload is not None:
            return blk.payload
        if blk.host_table is not None:
            return self.serialize_block_table(blk.host_table)
        batch = blk.spillable.get()
        return self.serialize_block_table(to_arrow(batch))

    def unregister_shuffle(self, shuffle_id: int) -> None:
        """ShuffleManager.unregisterShuffle analog — frees device store."""
        with self._lock:
            ids = self._by_shuffle.pop(shuffle_id, [])
            blocks = [self._blocks.pop(b) for b in ids if b in self._blocks]
        for b in blocks:
            if b.spillable is not None:
                b.spillable.close()


@dataclass
class ReceivedBuffer:
    temp_id: int
    table_meta: wire.TableMeta
    data: Optional[bytes]
    disk_path: Optional[str] = None   # pressure-spilled payload


class ShuffleReceivedBufferCatalog:
    """Reducer-side catalog of fetched buffers awaiting materialization
    (ShuffleReceivedBufferCatalog.scala:119; temp ids TempSpillBufferId
    :49).

    Pressure-aware: the catalog registers with the admission
    controller's memory-pressure hook (mem/spill.py), so in-flight
    received payloads — the pipelined exchange can hold several
    partitions' worth — spill host->disk under pressure instead of
    stalling admission; ``materialize`` reads a spilled payload back
    transparently.  Every add/release is counted
    (``shuffle.received.added``/``released``), making leak audits a
    registry diff instead of an internals spelunk."""

    def __init__(self):
        self._ids = itertools.count(1)
        self._received: Dict[int, ReceivedBuffer] = {}
        self._lock = threading.Lock()
        # serializes whole pressure_spill passes against each other
        # (two concurrent spillers would write and then orphan each
        # other's files); never held by add/materialize, so frame
        # intake keeps flowing while a spill writes
        self._spill_mutex = threading.Lock()
        self._spill_dir: Optional[str] = None
        self.pending_bytes = 0
        from spark_rapids_tpu.mem import spill as _spill
        _spill.register_pressure_spiller(self)

    def add(self, table_meta: wire.TableMeta, data: bytes) -> int:
        with self._lock:
            tid = next(self._ids)
            self._received[tid] = ReceivedBuffer(tid, table_meta, data)
            self.pending_bytes += len(data)
        from spark_rapids_tpu.obs import registry as obsreg
        obsreg.get_registry().inc("shuffle.received.added")
        return tid

    def pressure_spill(self, bytes_needed: int) -> int:
        """Move pending received payloads host->disk until
        ``bytes_needed`` host bytes are freed (oldest first — the
        consumer drains in partition order, so the oldest pending
        buffers are the furthest from consumption).

        Disk writes happen OUTSIDE the catalog lock: ``add`` runs on
        TCP reader threads as DATA frames complete, and blocking frame
        intake for a multi-buffer write exactly when the system is
        under pressure would invert the point.  A buffer that was
        materialized/freed while its file was being written just has
        the file discarded (the swap under the lock re-checks the
        payload identity)."""
        with self._lock:
            # pending_bytes is the aggregate this fast path rides:
            # handle_memory_pressure walks EVERY registered catalog
            # on a pressured admission, and most have nothing pending
            if self.pending_bytes <= 0:
                return 0
        with self._spill_mutex:
            return self._pressure_spill_locked(bytes_needed)

    def _pressure_spill_locked(self, bytes_needed: int) -> int:
        import shutil
        import tempfile
        import weakref
        freed = 0
        with self._lock:
            if self._spill_dir is None:
                self._spill_dir = tempfile.mkdtemp(
                    prefix="rapids_tpu_shuffle_recv_")
                # the directory (and any payload files stranded by an
                # error path) goes with the catalog — spilled buffers
                # hold files only between pressure and consumption
                self._spill_dir_finalizer = weakref.finalize(
                    self, shutil.rmtree, self._spill_dir,
                    ignore_errors=True)
            spill_dir = self._spill_dir
            victims = [(rb.temp_id, rb.data)
                       for rb in self._received.values()
                       if rb.data]
        for tid, data in victims:
            if freed >= bytes_needed:
                break
            path = os.path.join(spill_dir, f"recv_{tid}.bin")
            with open(path, "wb") as f:
                f.write(data)
            with self._lock:
                rb = self._received.get(tid)
                if rb is not None and rb.data is data:
                    rb.data = None
                    rb.disk_path = path
                    self.pending_bytes -= len(data)
                    freed += len(data)
                    continue
            # consumed (or freed) while we wrote: drop the orphan file
            try:
                os.unlink(path)
            except OSError:
                pass
        if freed:
            from spark_rapids_tpu.obs import registry as obsreg
            obsreg.get_registry().inc_many(
                ("spill.events", 1),
                ("shuffle.received.spilledBytes", freed))
        return freed

    @staticmethod
    def _payload(rb: ReceivedBuffer) -> bytes:
        if rb.data is not None:
            return rb.data
        with open(rb.disk_path, "rb") as f:
            return f.read()

    @staticmethod
    def _drop_disk(rb: ReceivedBuffer) -> None:
        if rb.disk_path is not None:
            try:
                os.unlink(rb.disk_path)
            except OSError:
                pass
            rb.disk_path = None

    def materialize(self, temp_id: int) -> pa.Table:
        """Decode the received payload into a host table and drop it.
        Degenerate blocks (no payload) are rebuilt from metadata alone,
        as the reference does (MetaUtils.scala:145)."""
        with self._lock:
            rb = self._received.pop(temp_id)
            if rb.data is not None:
                self.pending_bytes -= len(rb.data)
        data = self._payload(rb)
        self._drop_disk(rb)
        rb.data = data
        from spark_rapids_tpu.obs import registry as obsreg
        obsreg.get_registry().inc("shuffle.received.released")
        if rb.table_meta.is_degenerate:
            if not rb.table_meta.columns and rb.table_meta.num_rows:
                # pyarrow cannot represent a zero-column table with rows;
                # fail loudly rather than silently dropping the row count
                raise NotImplementedError(
                    f"zero-column block with {rb.table_meta.num_rows} rows "
                    "cannot be materialized as a pyarrow table")
            fields = [pa.field(c.name, _parse_arrow_type(c.dtype_code),
                               c.nullable)
                      for c in rb.table_meta.columns]
            schema = pa.schema(fields)
            return pa.table(
                {f.name: pa.array([], type=f.type) for f in fields},
                schema=schema)
        return deserialize_table(rb.data)

    def free(self, temp_id: int) -> None:
        """Drop a received buffer without materializing it — the
        iterator's error path releases undelivered fetches so an aborted
        read doesn't leak catalog entries."""
        with self._lock:
            rb = self._received.pop(temp_id, None)
            if rb is not None and rb.data is not None:
                self.pending_bytes -= len(rb.data)
        if rb is not None:
            self._drop_disk(rb)
            from spark_rapids_tpu.obs import registry as obsreg
            obsreg.get_registry().inc("shuffle.received.released")

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._received)
