"""ICI shuffle: device-resident partition exchange over a jax Mesh.

This is the TPU-native replacement for the reference's accelerated shuffle
data plane (reference: shuffle-plugin UCX transport, UCX.scala:53-533;
RapidsCachingWriter keeping map-output batches in the device store,
RapidsShuffleInternalManager.scala:90-155).  Where the reference moves
device buffers peer-to-peer over RDMA with bounce-buffer windowing, here
partitions never leave HBM at all: a ``shard_map`` region hash-partitions
rows on-device and swaps the buckets with one ``lax.all_to_all`` over the
ICI mesh axis — the collective formulation SURVEY.md §2g/§5 prescribes.

The flagship composite op is the distributed hash aggregate:

  local update-agg  ->  murmur3 pmod bucketize  ->  all_to_all  ->
  compact  ->  merge-agg  ->  final projection

which is exactly the reference's partial-agg / shuffle / final-agg stage
pair (aggregate.scala + GpuShuffleExchangeExec) fused into one SPMD step
XLA can schedule end-to-end.  Static shapes: each device sends exactly
``capacity`` candidate slots per peer; true counts travel as a tiny int
vector alongside (the scalar-prefetch idiom).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn
from spark_rapids_tpu.exec.tpu_aggregate import (finalize_aggregate,
                                                 make_spec, merge_aggregate,
                                                 update_aggregate)
from spark_rapids_tpu.exec.tpu_basic import compact
from spark_rapids_tpu.expr import ir
from spark_rapids_tpu.expr.eval_tpu import ColVal, hash_colval
from spark_rapids_tpu.plan.logical import Schema


def _shard_map(f, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: the top-level API (with
    ``check_vma``) when present, else the experimental module (whose
    equivalent knob is ``check_rep``).  Raises NotImplementedError with
    a skip-friendly reason when neither exists."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    try:
        from jax.experimental.shard_map import shard_map as _sm
    except ImportError as e:
        raise NotImplementedError(
            "this jax has neither jax.shard_map nor "
            "jax.experimental.shard_map — ICI shuffle unavailable"
        ) from e
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def partition_targets(key_vals: Sequence[ColVal], n_parts: int,
                      seed: int = 42) -> jnp.ndarray:
    """Spark-compatible murmur3 pmod partition ids
    (GpuHashPartitioning analog, reference: GpuHashPartitioning.scala:29)."""
    cap = key_vals[0].data.shape[0]
    h = jnp.full((cap,), np.int32(seed), dtype=jnp.int32)
    for v in key_vals:
        h = hash_colval(v, h)
    m = h % np.int32(n_parts)
    return jnp.where(m < 0, m + n_parts, m)


def bucketize(batch: DeviceBatch, target: jnp.ndarray, n_parts: int
              ) -> Tuple[List[DeviceColumn], jnp.ndarray]:
    """Slice a batch into n_parts contiguous buckets (stacked on a new
    leading axis).  The XLA analog of cudf contiguous_split used by
    GpuPartitioning.sliceInternalOnGpu (reference: GpuPartitioning.scala:45).

    Returns columns whose arrays have shape [n_parts, cap, ...] plus a
    per-bucket row count [n_parts].
    """
    cap = batch.capacity
    exists = batch.row_mask()
    t = jnp.where(exists, target, n_parts)  # park padding out of range
    counts = jnp.zeros((n_parts,), dtype=jnp.int32).at[t].add(
        exists.astype(jnp.int32), mode="drop")
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
    order = jnp.argsort(t, stable=True)  # groups rows by target, padding last
    sorted_t = jnp.take(t, order)
    rank = jnp.arange(cap, dtype=jnp.int32) - jnp.take(
        offsets, jnp.clip(sorted_t, 0, n_parts - 1))
    flat_pos = jnp.where(sorted_t < n_parts,
                         sorted_t * cap + jnp.clip(rank, 0, cap - 1),
                         n_parts * cap)  # padding -> dropped
    gather_idx = jnp.zeros((n_parts * cap,), dtype=jnp.int32).at[
        flat_pos].set(order.astype(jnp.int32), mode="drop")
    slot = jnp.arange(n_parts * cap) % cap
    valid = slot < jnp.repeat(counts, cap)
    out_cols = []
    for c in batch.columns:
        g = c.gather(gather_idx, valid)
        data = g.data.reshape((n_parts, cap) + g.data.shape[1:])
        validity = g.validity.reshape((n_parts, cap))
        lengths = g.lengths.reshape((n_parts, cap)) \
            if g.lengths is not None else None
        ev = g.elem_validity.reshape((n_parts, cap) +
                                     g.elem_validity.shape[1:]) \
            if g.elem_validity is not None else None
        out_cols.append(DeviceColumn(c.dtype, data, validity, lengths, ev))
    return out_cols, counts


def exchange(stacked_cols: List[DeviceColumn], counts: jnp.ndarray,
             axis: str) -> Tuple[List[DeviceColumn], jnp.ndarray]:
    """One tiled all_to_all per buffer: bucket d of device s lands on
    device d as block s.  (The whole UCX client/server/bounce-buffer
    machinery of the reference collapses into this collective.)"""
    def a2a(x):
        return lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                              tiled=True)
    out_cols = []
    for c in stacked_cols:
        out_cols.append(DeviceColumn(
            c.dtype, a2a(c.data), a2a(c.validity),
            a2a(c.lengths) if c.lengths is not None else None,
            a2a(c.elem_validity) if c.elem_validity is not None else None))
    return out_cols, a2a(counts)


def reassemble(names: Sequence[str], stacked_cols: List[DeviceColumn],
               counts_recv: jnp.ndarray) -> DeviceBatch:
    """Flatten received blocks and compact valid rows to the front."""
    n_parts = counts_recv.shape[0]
    cap = stacked_cols[0].validity.shape[1]
    slot = jnp.arange(n_parts * cap) % cap
    valid = slot < jnp.repeat(counts_recv, cap)
    flat_cols = []
    for c in stacked_cols:
        data = c.data.reshape((n_parts * cap,) + c.data.shape[2:])
        validity = c.validity.reshape((n_parts * cap,))
        lengths = c.lengths.reshape((n_parts * cap,)) \
            if c.lengths is not None else None
        ev = c.elem_validity.reshape((n_parts * cap,) +
                                     c.elem_validity.shape[2:]) \
            if c.elem_validity is not None else None
        flat_cols.append(DeviceColumn(c.dtype, data, validity, lengths, ev))
    # rows arrive block-strided; compact the `valid` rows to the front so
    # the result satisfies the DeviceBatch row_mask contract (scatter by
    # cumsum rank — no sort; XLA sort compiles are minutes-scale)
    tcap = n_parts * cap
    count = jnp.sum(valid.astype(jnp.int32))
    dest = jnp.where(valid, jnp.cumsum(valid.astype(jnp.int32)) - 1,
                     tcap)
    from spark_rapids_tpu.columnar.batch import compact_arrays
    cols = [DeviceColumn(c.dtype, *compact_arrays(
        valid, dest, c.data, c.validity, c.lengths, c.elem_validity))
        for c in flat_cols]
    return DeviceBatch(names, cols, count)


def make_distributed_agg_step(mesh: Mesh, axis: str,
                              schema: Schema,
                              groupings: Sequence[ir.Expression],
                              aggregates: Sequence[ir.AggregateExpression],
                              out_names: Sequence[str]):
    """Build the jitted SPMD step: sharded input columns -> per-device
    aggregated output shard.

    Inputs are global arrays sharded on the leading (row) axis over
    ``axis``; ``local_rows`` is an [n_devices] vector of true per-shard row
    counts.  Output shards hold disjoint group subsets (hash-partitioned),
    exactly like the reference's final-aggregate stage after a hash
    exchange.
    """
    specs = [make_spec(a) for a in aggregates]
    nk = len(groupings)
    n_dev = mesh.shape[axis]
    names = schema.names
    dtypes = schema.dtypes

    # the distributed aggregate stays on the XLA segment reductions:
    # Pallas kernels under shard_map are unvalidated on this runtime.
    # Make the stand-down OBSERVABLE when pallas was requested (the
    # every-selection-is-counted contract, kernels/backend.py) — one
    # tagged fallback per plan build, host-side, outside the trace.
    from spark_rapids_tpu.kernels import backend as _kb
    if _kb.default_backend() == _kb.PALLAS:
        _kb.fallback("agg.segreduce", "ici_distributed")

    def local_step(cols_leaves, local_rows):
        cols = _leaves_to_cols(cols_leaves, dtypes)
        batch = DeviceBatch(names, cols, local_rows[0])
        partial = update_aggregate(batch, groupings, aggregates, specs,
                                   backend="xla")
        key_vals = [ColVal(c.dtype, c.data, c.validity, c.lengths)
                    for c in partial.columns[:nk]]
        target = partition_targets(key_vals, n_dev) if nk else \
            jnp.zeros((partial.capacity,), dtype=jnp.int32)
        stacked, counts = bucketize(partial, target, n_dev)
        stacked, counts_recv = exchange(stacked, counts, axis)
        received = reassemble(partial.names, stacked, counts_recv)
        merged = merge_aggregate(received, nk, specs, backend="xla")
        final = finalize_aggregate(merged, nk, specs, out_names)
        out_leaves = _cols_to_leaves(final.columns)
        return out_leaves, jnp.reshape(
            jnp.asarray(final.num_rows, dtype=jnp.int32), (1,))

    in_specs = (_col_specs(dtypes, P(axis)), P(axis))
    out_dtypes = _probe_out_dtypes(schema, groupings, aggregates, out_names)
    out_specs = (_col_specs(out_dtypes, P(axis)), P(axis))

    step = _shard_map(local_step, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs)
    return jax.jit(step), out_dtypes


def _probe_out_dtypes(schema, groupings, aggregates, out_names):
    for g in groupings:
        g.resolve() if g.dtype is None else None
    key_dts = [g.dtype for g in groupings]
    agg_dts = [a.dtype for a in aggregates]
    return key_dts + agg_dts


def _col_specs(dtypes, spec):
    out = []
    for d in dtypes:
        if d.is_string:
            out.append((spec, spec, spec))
        else:
            out.append((spec, spec))
    return tuple(out)


def _cols_to_leaves(cols: Sequence[DeviceColumn]):
    leaves = []
    for c in cols:
        if c.elem_validity is not None:
            leaves.append((c.data, c.validity, c.lengths, c.elem_validity))
        elif c.lengths is not None:
            leaves.append((c.data, c.validity, c.lengths))
        else:
            leaves.append((c.data, c.validity))
    return tuple(leaves)


def _leaves_to_cols(leaves, dtypes):
    cols = []
    for leaf, d in zip(leaves, dtypes):
        if len(leaf) == 4:
            cols.append(DeviceColumn(d, leaf[0], leaf[1], leaf[2], leaf[3]))
        elif len(leaf) == 3:
            cols.append(DeviceColumn(d, leaf[0], leaf[1], leaf[2]))
        else:
            cols.append(DeviceColumn(d, leaf[0], leaf[1], None))
    return cols


def shard_batch(batch: DeviceBatch, mesh: Mesh, axis: str
                ) -> Tuple[Tuple, jnp.ndarray]:
    """Distribute a host-built DeviceBatch's rows round-robin-contiguously
    across the mesh: returns (sharded column leaves, per-shard row counts).

    The capacity must divide evenly by the device count; rows are laid out
    so shard i holds rows [i*local_cap, (i+1)*local_cap).
    """
    n_dev = mesh.shape[axis]
    cap = batch.capacity
    assert cap % n_dev == 0, f"capacity {cap} not divisible by {n_dev}"
    local_cap = cap // n_dev
    total = int(batch.num_rows)
    # per-shard true row counts for the contiguous layout
    counts = np.clip(total - np.arange(n_dev) * local_cap, 0, local_cap)
    counts = jnp.asarray(counts, dtype=jnp.int32)
    sharding = NamedSharding(mesh, P(axis))
    leaves = []
    for c in batch.columns:
        # leaf arity must match _cols_to_leaves: 4-tuple implies lengths
        assert c.elem_validity is None or c.lengths is not None
        leaf = [jax.device_put(c.data, sharding),
                jax.device_put(c.validity, sharding)]
        if c.lengths is not None:
            leaf.append(jax.device_put(c.lengths, sharding))
        if c.elem_validity is not None:
            leaf.append(jax.device_put(c.elem_validity, sharding))
        leaves.append(tuple(leaf))
    counts = jax.device_put(counts, sharding)
    return tuple(leaves), counts


# ---------------------------------------------------------------------------
# Generic partition exchange: the ICI data plane behind
# TpuShuffleExchangeExec(transport='ici').  Reference analog: the UCX
# transport implementation behind the shuffle SPI
# (shuffle-plugin/.../UCX.scala:53-533) — here the entire peer-to-peer
# client/server machinery collapses into one lax.all_to_all over the mesh.
# ---------------------------------------------------------------------------

_DEFAULT_MESH: Optional[Mesh] = None
_STEP_CACHE = {}


def get_default_mesh() -> Mesh:
    """Process-wide 1-D mesh over every visible device (the 'shuffle'
    axis).  On the 8-virtual-CPU test platform this is an 8-way mesh; on a
    single real TPU chip it degenerates to 1 device (all_to_all becomes an
    identity, keeping one code path)."""
    global _DEFAULT_MESH
    if _DEFAULT_MESH is None:
        _DEFAULT_MESH = Mesh(np.array(jax.devices()), ("shuffle",))
    return _DEFAULT_MESH


def with_capacity(batch: DeviceBatch, cap: int) -> DeviceBatch:
    """Re-capacity a front-compacted batch (grow or shrink padding)."""
    if batch.capacity == cap:
        return batch
    assert int(batch.num_rows) <= cap
    from spark_rapids_tpu.shuffle.exchange import slice_span
    return slice_span(batch, jnp.int32(0),
                      jnp.asarray(batch.num_rows, jnp.int32), cap)


def make_exchange_step(mesh: Mesh, axis: str, names, dtypes, aux_key):
    """Jitted shard_map step routing rows to the device owning their
    target partition.  The batch's LAST column is the int32 target
    partition id; device d owns partitions {p : p % n_dev == d}.

    Returns out leaves of per-device capacity n_dev*local_cap (worst case:
    every row lands on one device) plus per-device received row counts.
    """
    key = (mesh, axis, tuple(names), aux_key)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]
    n_dev = mesh.shape[axis]

    def local_step(leaves, local_rows):
        cols = _leaves_to_cols(leaves, dtypes)
        batch = DeviceBatch(names, cols, local_rows[0])
        part = batch.columns[-1].data.astype(jnp.int32)
        owner = part % np.int32(n_dev)
        stacked, counts = bucketize(batch, owner, n_dev)
        stacked, counts_recv = exchange(stacked, counts, axis)
        received = reassemble(names, stacked, counts_recv)
        return _cols_to_leaves(received.columns), jnp.reshape(
            jnp.asarray(received.num_rows, dtype=jnp.int32), (1,))

    step = jax.jit(_shard_map(
        local_step, mesh=mesh, in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P(axis))))
    _STEP_CACHE[key] = step
    return step


def split_shards(arr: jnp.ndarray, n_dev: int) -> List[jnp.ndarray]:
    """Per-device local views of a leading-axis-sharded global array,
    without any collective (each view stays committed to its device)."""
    per = arr.shape[0] // n_dev
    shards = {s.index[0].start or 0: s.data for s in arr.addressable_shards}
    if len(shards) == n_dev and all(d * per in shards
                                    for d in range(n_dev)):
        return [shards[d * per] for d in range(n_dev)]
    return [arr[d * per:(d + 1) * per] for d in range(n_dev)]


def exchange_batch(batch: DeviceBatch, targets: jnp.ndarray,
                   min_bucket: int = 16
                   ) -> Tuple[List[Optional[DeviceBatch]], Mesh]:
    """Run the full ICI exchange for one global batch.

    ``targets`` is a per-slot int32 target-partition vector (padding slots
    ignored).  Returns one local DeviceBatch per mesh device — each batch
    carries a trailing '__part__' column so the reader can sub-split the
    device's rows into its owned partitions — plus the mesh used.
    """
    from spark_rapids_tpu.columnar.batch import bucket_rows

    mesh = get_default_mesh()
    n_dev = mesh.shape["shuffle"]
    total = int(batch.num_rows)
    part_col = DeviceColumn(dt.INT32, targets.astype(jnp.int32),
                            batch.row_mask(), None)
    aug = DeviceBatch(list(batch.names) + ["__part__"],
                      list(batch.columns) + [part_col], total)
    local_cap = bucket_rows((total + n_dev - 1) // n_dev, min_bucket)
    aug = with_capacity(aug, local_cap * n_dev)
    leaves, counts = shard_batch(aug, mesh, "shuffle")
    aux_key = tuple((c.dtype.name, c.data.shape[1:],
                     c.lengths is not None, c.elem_validity is not None)
                    for c in aug.columns) + (local_cap,)
    step = make_exchange_step(mesh, "shuffle", aug.names, aug.dtypes,
                              aux_key)
    out_leaves, out_rows = step(leaves, counts)
    rows = np.asarray(out_rows)
    dev_batches: List[Optional[DeviceBatch]] = []
    for d in range(n_dev):
        if int(rows[d]) == 0:
            dev_batches.append(None)
            continue
        cols = []
        for leaf, c in zip(out_leaves, aug.columns):
            parts = [split_shards(a, n_dev)[d] for a in leaf]
            lengths = parts[2] if c.lengths is not None else None
            ev = parts[-1] if c.elem_validity is not None else None
            cols.append(DeviceColumn(c.dtype, parts[0], parts[1],
                                     lengths, ev))
        dev_batches.append(DeviceBatch(aug.names, cols, int(rows[d])))
    return dev_batches, mesh


def ring_broadcast_batch(batch: DeviceBatch) -> dict:
    """Build replication over the POINT-TO-POINT plane: the batch is
    sharded across the mesh and each shard travels around the ICI ring
    with ``lax.ppermute`` (collective_permute) until every device holds
    every shard — n_dev-1 neighbor hops instead of one all-to-all, the
    memory-traffic shape of a ring all-gather.

    This is the engine's collective formulation of the reference's
    tag-matched per-peer pulls (UCXConnection.scala:385: each reducer
    fetches specific blocks from specific peers); BASELINE.json's north
    star names ICI all_to_all AND collective_permute as the two data
    planes.  Same {device: DeviceBatch} contract as broadcast_batch."""
    from spark_rapids_tpu.columnar.batch import bucket_rows

    mesh = get_default_mesh()
    n_dev = mesh.shape["shuffle"]
    if n_dev == 1:
        return broadcast_batch(batch)
    total = int(batch.num_rows)
    local_cap = bucket_rows(max((total + n_dev - 1) // n_dev, 1), 16)
    aug = with_capacity(batch, local_cap * n_dev)
    leaves, counts = shard_batch(aug, mesh, "shuffle")
    names = aug.names
    # each device sends its current block to its LEFT neighbor, so after
    # k hops a device holds the block of (its index + k) % n_dev
    perm = [(i, (i - 1) % n_dev) for i in range(n_dev)]

    def local_step(cols_leaves, local_rows):
        me = lax.axis_index("shuffle")
        flat, treedef = jax.tree_util.tree_flatten(
            (cols_leaves, local_rows))
        accs = [jnp.zeros((n_dev,) + a.shape, a.dtype) for a in flat]
        cur = list(flat)
        for k in range(n_dev):
            pos = (me + k) % np.int32(n_dev)
            accs = [jax.lax.dynamic_update_slice(
                acc, c[None], (pos,) + (jnp.int32(0),) * c.ndim)
                for acc, c in zip(accs, cur)]
            if k < n_dev - 1:
                cur = [lax.ppermute(c, "shuffle", perm) for c in cur]
        # accs are IDENTICAL on every device now: [n_dev, ...] blocks in
        # global shard order — rebuild stacked columns and compact
        g_cols_leaves, g_rows = jax.tree_util.tree_unflatten(
            treedef, accs)
        stacked: List[DeviceColumn] = []
        for c, leaf in zip(aug.columns, g_cols_leaves):
            parts = list(leaf)
            lengths = parts[2] if c.lengths is not None else None
            ev = parts[-1] if c.elem_validity is not None else None
            stacked.append(DeviceColumn(c.dtype, parts[0], parts[1],
                                        lengths, ev))
        counts_recv = jnp.reshape(g_rows, (n_dev,))
        out = reassemble(names, stacked, counts_recv)
        return _cols_to_leaves(out.columns), jnp.reshape(
            jnp.asarray(out.num_rows, jnp.int32), (1,))

    step = jax.jit(_shard_map(
        local_step, mesh=mesh, in_specs=(P("shuffle"), P("shuffle")),
        out_specs=(P(), P())))
    out_leaves, out_rows = step(leaves, counts)
    n_out = int(np.asarray(out_rows)[0])

    out = {}
    for d in mesh.devices.flat:
        def local(a, d=d):
            if a is None or not hasattr(a, "addressable_shards"):
                return a
            for s in a.addressable_shards:
                if s.device == d:
                    return s.data
            return a
        cols = []
        for leaf, c in zip(out_leaves, aug.columns):
            parts = [local(a) for a in leaf]
            lengths = parts[2] if c.lengths is not None else None
            ev = parts[-1] if c.elem_validity is not None else None
            cols.append(DeviceColumn(c.dtype, parts[0], parts[1],
                                     lengths, ev))
        out[d] = DeviceBatch(names, cols, n_out)
    return out


def broadcast_batch(batch: DeviceBatch) -> dict:
    """One-to-all replication of a batch over the mesh: ONE
    fully-replicated ``jax.device_put`` lets XLA broadcast every column
    over ICI, then each device gets a zero-copy local view.

    The mesh sibling of ``exchange_batch`` (all-to-all) — the
    ``GpuBroadcastExchangeExec`` analog (reference:
    GpuBroadcastExchangeExec.scala:238-398, which serializes the build
    side once and ships it to every executor).  Returns
    {device: DeviceBatch} with one entry per mesh device."""
    mesh = get_default_mesh()
    rep = NamedSharding(mesh, P())
    rep_batch = jax.device_put(batch, rep)
    out = {}
    for d in mesh.devices.flat:
        def local(a, d=d):
            if a is None or not hasattr(a, "addressable_shards"):
                return a
            for s in a.addressable_shards:
                if s.device == d:
                    return s.data
            return a
        cols = [DeviceColumn(c.dtype, local(c.data), local(c.validity),
                             local(c.lengths), local(c.elem_validity))
                for c in rep_batch.columns]
        out[d] = DeviceBatch(batch.names, cols,
                             local(rep_batch.num_rows))
    return out
