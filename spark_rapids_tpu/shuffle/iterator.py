"""Reducer-side shuffle iterator over local + remote blocks.

Reference analog (SURVEY.md §2f): ``RapidsShuffleIterator.scala:49-365``
— splits block locations into local (served straight from the catalog)
and remote (fetched via transport clients), acquires the device semaphore
per produced batch, and surfaces failures as fetch-failed / timeout
exceptions so the scheduler can re-run the map stage
(RapidsShuffleExceptions.scala:21-32).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import pyarrow as pa

from spark_rapids_tpu.columnar.batch import to_arrow
from spark_rapids_tpu.mem.device import tpu_semaphore
from spark_rapids_tpu.shuffle.catalogs import (ShuffleBufferCatalog,
                                               ShuffleReceivedBufferCatalog)
from spark_rapids_tpu.shuffle.client import RapidsShuffleClient
from spark_rapids_tpu.shuffle.serializer import deserialize_table


class RapidsShuffleFetchFailedException(Exception):
    """Reference: RapidsShuffleFetchFailedException — a Spark
    FetchFailedException, so the map stage is retried."""


class RapidsShuffleTimeoutException(Exception):
    """Reference: RapidsShuffleTimeoutException
    (RapidsShuffleIterator.scala:188,345-361)."""


@dataclass
class RemoteSource:
    peer_executor_id: str
    client: RapidsShuffleClient
    map_ids: Optional[List[int]] = None


class RapidsShuffleIterator:
    """Yields host tables for one reduce partition, mixing local catalog
    hits with remote transport fetches."""

    def __init__(self, shuffle_id: int, reduce_id: int,
                 local_catalog: Optional[ShuffleBufferCatalog],
                 remotes: List[RemoteSource],
                 received_catalog: ShuffleReceivedBufferCatalog,
                 timeout_s: float = 30.0):
        self.shuffle_id = shuffle_id
        self.reduce_id = reduce_id
        self.local_catalog = local_catalog
        self.remotes = remotes
        self.received = received_catalog
        self.timeout_s = timeout_s

    def __iter__(self) -> Iterator[pa.Table]:
        # local blocks: straight from the device store
        # (RapidsCachingReader local path, RapidsCachingReader.scala:170)
        if self.local_catalog is not None:
            for blk in self.local_catalog.blocks_for(self.shuffle_id,
                                                     self.reduce_id):
                with tpu_semaphore():
                    if blk.host_table is not None:
                        yield blk.host_table
                    else:
                        yield to_arrow(blk.spillable.get())

        # remote blocks: async fetch per peer, drain a completion queue
        if not self.remotes:
            return
        q: "queue.Queue[Tuple[str, Optional[int], Optional[str]]]" = \
            queue.Queue()
        outstanding = len(self.remotes)

        for src in self.remotes:
            def make_cbs(peer: str):
                def on_batch(temp_id: int) -> None:
                    q.put(("batch", temp_id, None))

                def on_done(err: Optional[str]) -> None:
                    q.put(("done", None, err))
                return on_batch, on_done

            on_batch, on_done = make_cbs(src.peer_executor_id)
            src.client.do_fetch(self.shuffle_id, self.reduce_id,
                                src.map_ids, on_batch, on_done)

        while outstanding > 0:
            try:
                kind, temp_id, err = q.get(timeout=self.timeout_s)
            except queue.Empty:
                raise RapidsShuffleTimeoutException(
                    f"shuffle {self.shuffle_id} reduce {self.reduce_id}: "
                    f"no progress for {self.timeout_s}s "
                    f"({outstanding} peers outstanding)")
            if kind == "done":
                outstanding -= 1
                if err is not None:
                    raise RapidsShuffleFetchFailedException(
                        f"shuffle {self.shuffle_id} reduce "
                        f"{self.reduce_id}: {err}")
            else:
                with tpu_semaphore():
                    yield self.received.materialize(temp_id)
