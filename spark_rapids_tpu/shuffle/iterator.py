"""Reducer-side shuffle iterator over local + remote blocks.

Reference analog (SURVEY.md §2f): ``RapidsShuffleIterator.scala:49-365``
— splits block locations into local (served straight from the catalog)
and remote (fetched via transport clients), acquires the device semaphore
per produced batch, and surfaces failures as fetch-failed / timeout
exceptions so the scheduler can re-run the map stage
(RapidsShuffleExceptions.scala:21-32).

Recovery extensions beyond the reference:

* **Per-peer fetch retry**: a failed or timed-out peer fetch is
  re-issued up to ``max_retries`` times with exponential backoff +
  deterministic jitter, re-requesting only the missing map outputs
  (blocks already delivered are carried in the attempt's
  ``FetchHandle.completed_buffer_ids`` and skipped).  ``max_retries=0``
  restores fail-fast: the first fault raises the typed exceptions.
* **Clean error path**: before raising, every outstanding fetch is
  cancelled and undelivered received-buffer catalog entries are freed,
  so late ``on_batch``/``on_done`` callbacks can neither enqueue into a
  dead queue nor leak buffers.
"""

from __future__ import annotations

import queue
import random
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Set, Tuple

import pyarrow as pa

from spark_rapids_tpu.columnar.batch import to_arrow
from spark_rapids_tpu.mem.device import tpu_semaphore
from spark_rapids_tpu.sched import cancel as _cancel
from spark_rapids_tpu.shuffle import faults
from spark_rapids_tpu.shuffle.catalogs import (ShuffleBufferCatalog,
                                               ShuffleReceivedBufferCatalog)
from spark_rapids_tpu.shuffle.client import (FetchHandle,
                                             RapidsShuffleClient)


class RapidsShuffleFetchFailedException(Exception):
    """Reference: RapidsShuffleFetchFailedException — a Spark
    FetchFailedException, so the map stage is retried."""


class RapidsShuffleTimeoutException(Exception):
    """Reference: RapidsShuffleTimeoutException
    (RapidsShuffleIterator.scala:188,345-361)."""


@dataclass
class RemoteSource:
    peer_executor_id: str
    client: RapidsShuffleClient
    map_ids: Optional[List[int]] = None
    # retry hook: returns a fresh client (reconnecting if the transport
    # connection died); without it retries reuse the existing client
    refresh: Optional[Callable[[], RapidsShuffleClient]] = None


class _PeerFetch:
    """Mutable per-peer retry state for one iterator read."""

    def __init__(self, src: RemoteSource):
        self.src = src
        self.attempts = 0
        self.handle: Optional[FetchHandle] = None
        self.skip: Set[int] = set()
        self.done = False


class RapidsShuffleIterator:
    """Yields host tables for one reduce partition, mixing local catalog
    hits with remote transport fetches."""

    def __init__(self, shuffle_id: int, reduce_id: int,
                 local_catalog: Optional[ShuffleBufferCatalog],
                 remotes: List[RemoteSource],
                 received_catalog: ShuffleReceivedBufferCatalog,
                 timeout_s: float = 30.0,
                 max_retries: int = 0,
                 retry_backoff_ms: float = 50.0):
        self.shuffle_id = shuffle_id
        self.reduce_id = reduce_id
        self.local_catalog = local_catalog
        self.remotes = remotes
        self.received = received_catalog
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_ms / 1000.0
        # deterministic jitter: keyed by what we're reading, not wall time
        self._rng = random.Random((shuffle_id * 1_000_003 + reduce_id)
                                  & 0xFFFF_FFFF)

    def __iter__(self) -> Iterator[pa.Table]:
        # local blocks: straight from the device store
        # (RapidsCachingReader local path, RapidsCachingReader.scala:170)
        if self.local_catalog is not None:
            for blk in self.local_catalog.blocks_for(self.shuffle_id,
                                                     self.reduce_id):
                with tpu_semaphore():
                    if blk.host_table is not None:
                        yield blk.host_table
                    else:
                        yield to_arrow(blk.spillable.get())

        # remote blocks: async fetch per peer, drain a completion queue
        if not self.remotes:
            return
        stats = faults.get_fault_stats()
        # entries: ("batch", temp_id, None, None) or
        #          ("done", peer_id, error, attempt_epoch)
        q: "queue.Queue[Tuple[str, object, Optional[str], Optional[int]]]" \
            = queue.Queue()
        alive = {"ok": True}
        peers: Dict[str, _PeerFetch] = {
            src.peer_executor_id: _PeerFetch(src)
            for src in self.remotes}

        def drain_free() -> None:
            while True:
                try:
                    kind, a, _err, _ep = q.get_nowait()
                except queue.Empty:
                    return
                if kind == "batch":
                    self.received.free(a)

        def issue(p: _PeerFetch) -> None:
            peer_id = p.src.peer_executor_id
            epoch = p.attempts

            def on_batch(temp_id: int) -> None:
                if alive["ok"]:
                    q.put(("batch", temp_id, None, None))
                    if not alive["ok"]:
                        # abort raced our put after its drain: whoever
                        # observes the dead flag last cleans the queue
                        drain_free()
                else:
                    # late delivery into a finished/aborted read: free
                    # the buffer instead of enqueueing into a dead queue
                    self.received.free(temp_id)

            def on_done(err: Optional[str]) -> None:
                if alive["ok"]:
                    q.put(("done", peer_id, err, epoch))

            client = p.src.client
            if p.attempts and p.src.refresh is not None:
                client = p.src.refresh()
                p.src.client = client
            # None = first attempt; a retry passes a (possibly empty)
            # set so the client suppresses degenerate re-delivery even
            # when no real block completed before the failure
            p.handle = client.do_fetch(
                self.shuffle_id, self.reduce_id, p.src.map_ids,
                on_batch, on_done,
                skip_buffer_ids=set(p.skip) if p.attempts else None)

        def abort() -> None:
            """Error-path cleanup: cancel outstanding fetches, then
            drain and free every received-but-unyielded buffer."""
            alive["ok"] = False
            for p in peers.values():
                if p.handle is not None:
                    p.handle.cancel()
            drain_free()

        def backoff(attempts: int) -> None:
            from spark_rapids_tpu.shuffle.transport import backoff_delay_s
            time.sleep(backoff_delay_s(self.retry_backoff_s, attempts,
                                       self._rng, cap_s=5.0))

        def retry(p: _PeerFetch, do_sleep: bool = True) -> bool:
            """Cancel the failed attempt and re-issue the fetch for only
            the missing map outputs; False when retries are exhausted."""
            if p.attempts >= self.max_retries:
                return False
            if p.handle is not None:
                # cancel FIRST: freezes completed_buffer_ids, so every
                # block counted as delivered stays delivered exactly once
                p.handle.cancel()
                p.skip |= p.handle.completed_buffer_ids
            p.attempts += 1
            stats.incr("retries")
            if do_sleep:
                backoff(p.attempts)
            issue(p)
            return True

        for p in peers.values():
            issue(p)
        outstanding = len(peers)

        # cancellation wake-up: a fired CancelToken pushes a sentinel
        # into the completion queue so a reader blocked in q.get() stops
        # immediately instead of riding out the progress timeout; the
        # drain loop then aborts (FetchHandle.cancel per peer + freeing
        # every received-but-unyielded catalog buffer) and re-raises
        token = _cancel.current()
        waker = None
        if token is not None:
            def waker() -> None:
                q.put(("cancel", None, None, None))
            token.add_callback(waker)
        try:
            yield from self._drain_remote(q, peers, outstanding, alive,
                                          retry, abort, backoff, stats,
                                          token)
        finally:
            if token is not None and waker is not None:
                token.remove_callback(waker)
            # every exit — completion, error, or an abandoned read
            # (GeneratorExit) — cancels what's still in flight and frees
            # undelivered buffers; a no-op after a clean drain
            abort()

    def _drain_remote(self, q, peers, outstanding, alive, retry, abort,
                      backoff, stats, token=None) -> Iterator[pa.Table]:
        while outstanding > 0:
            if token is not None and token.is_cancelled:
                abort()
                token.check()
            try:
                kind, a, err, epoch = q.get(timeout=self.timeout_s)
            except queue.Empty:
                stats.incr("timeouts")
                stalled = [p for p in peers.values() if not p.done]
                if stalled and all(p.attempts < self.max_retries
                                   for p in stalled):
                    # one shared sleep for the whole stalled group, not
                    # a per-peer sum of sequential backoffs
                    backoff(max(p.attempts for p in stalled) + 1)
                    for p in stalled:
                        retry(p, do_sleep=False)
                    continue
                abort()
                raise RapidsShuffleTimeoutException(
                    f"shuffle {self.shuffle_id} reduce {self.reduce_id}: "
                    f"no progress for {self.timeout_s}s "
                    f"({outstanding} peers outstanding)")
            if kind == "cancel":
                abort()
                if token is not None:
                    token.check()
                raise _cancel.QueryCancelledError(
                    f"shuffle {self.shuffle_id} reduce "
                    f"{self.reduce_id}: read cancelled")
            if kind == "done":
                p = peers[a]
                if epoch != p.attempts or p.done:
                    continue  # stale completion from a cancelled attempt
                if err is None:
                    p.done = True
                    outstanding -= 1
                elif not retry(p):
                    abort()
                    raise RapidsShuffleFetchFailedException(
                        f"shuffle {self.shuffle_id} reduce "
                        f"{self.reduce_id}: {err} "
                        f"(after {p.attempts} retries)")
            else:
                try:
                    with tpu_semaphore():
                        t = self.received.materialize(a)
                except Exception as e:
                    # a corrupted payload decodes to garbage: that is a
                    # data-plane failure (stage retry), not a crash
                    abort()
                    raise RapidsShuffleFetchFailedException(
                        f"shuffle {self.shuffle_id} reduce "
                        f"{self.reduce_id}: undecodable received "
                        f"block: {e}") from e
                yield t
