"""TCP socket shuffle transport: the cross-process/DCN data plane.

Reference analog: the UCX transport plugin
(``shuffle-plugin/.../ucx/UCX.scala:53-533``) — a TCP management
handshake (UCX.scala:192-246) plus tag-matched buffer transfers
(UCX.scala:247-311) behind the ``RapidsShuffleTransport`` SPI.  On TPU
pods the intra-slice data plane is ICI collectives (shuffle/ici.py); this
transport is the DCN stand-in that moves shuffle bytes BETWEEN engine
processes/hosts, proving the client/server/iterator state machines over a
real process boundary (the round-3 gap: only the in-process loopback
existed).

Wire protocol (little-endian, length-prefixed frames like
pyworker/worker.py):

    frame   := u8 kind, u64 tag, u32 len, len bytes
    HELLO   := kind 0, payload = client executor id (utf-8); sent once
               per connection so the server can route streaming DATA
               frames back over the same socket (the reference's
               "rapids=<port>" MapStatus topology plays this role)
    REQ     := kind 1, tag = request id, payload = control frame
    RESP    := kind 2, tag = request id, payload = response frame
    DATA    := kind 3, tag = transfer tag, payload = buffer bytes

Tag-matched receives reuse the loopback's rendezvous channel
(shuffle/local.py _TagChannel): the socket reader posts arriving DATA
frames as "sends" into the channel, client code posts receives — sends
arriving before their matching receive queue, exactly UCX's
expected-tag semantics.
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

from spark_rapids_tpu.shuffle import faults
from spark_rapids_tpu.shuffle.local import _TagChannel
from spark_rapids_tpu.shuffle.transport import (ClientConnection,
                                                ServerConnection,
                                                ShuffleTransport,
                                                Transaction,
                                                TransactionStatus)

_HELLO, _REQ, _RESP, _DATA, _ERR = 0, 1, 2, 3, 4
_HDR = struct.Struct("<BQI")

# ---------------------------------------------------------------------------
# Per-frame DATA compression (the compressed DCN leg)
#
# Negotiated in the HELLO handshake: the client appends "\0<codec>" to
# its executor id (announcing what it RESOLVED, so a degraded end
# negotiates "zlib", never a name it can't decode natively); a server
# that accepts the suffix wraps EVERY DATA payload to that peer as
# u8 flag (0 raw / 1 compressed / 2 stdlib-zlib-fallback compressed),
# u32 uncompressed_size, body  — so incompressible or empty frames
# ride flag-0 with no size inflation beyond the 5-byte header, and
# the length-prefixed frame layout itself is unchanged.  Flag 2 marks
# frames from a SENDER whose own resolution degraded (it lacks the
# negotiated codec): the receiver decodes them with stdlib zlib
# regardless of what it negotiated, so availability drift between the
# two processes can never silently poison the stream.  The codec runs
# on the wire payload (the already-serialized Arrow IPC block
# windows), shrinking the transfer leg on compressible columnar data.
# ---------------------------------------------------------------------------

_WIRE_WRAP = struct.Struct("<BI")
_WIRE_RAW, _WIRE_COMPRESSED, _WIRE_FALLBACK = 0, 1, 2


class WireCodec:
    """One per-frame compression codec (name + compress/decompress)."""

    def __init__(self, name: str, compress: Callable[[bytes], bytes],
                 decompress: Callable[[bytes, int], bytes],
                 fallback: bool = False):
        self.name = name
        self.compress = compress
        self.decompress = decompress
        # stdlib zlib standing in for another name: announced as
        # "zlib" when this end negotiates, and marked on the wire
        # (``_WIRE_FALLBACK``) when this end compresses — the peer
        # must never assume the negotiated NAME's bitstream from an
        # end whose resolution degraded (split-brain poisoning)
        self.fallback = fallback


def negotiated_name(codec: "WireCodec") -> str:
    """The codec name this end should announce in its HELLO: a
    degraded resolution negotiates the implementation it will actually
    run ("zlib"), not the name it failed to load."""
    return "zlib" if codec.fallback else codec.name


def _zlib_codec(name: str) -> WireCodec:
    return WireCodec(name, lambda b: zlib.compress(b, 1),
                     lambda b, n: zlib.decompress(b),
                     fallback=(name != "zlib"))


def _make_wire_codec(name: str) -> WireCodec:
    """lz4/zstd ride pyarrow's codecs (already shipping in the image for
    IPC buffer compression); an unavailable codec degrades to the
    stdlib zlib implementation — both ends of a connection resolve the
    NAME through this same table, so the negotiated stream stays
    self-consistent."""
    if name == "zlib":
        return _zlib_codec(name)
    try:
        import pyarrow as pa
        if pa.Codec.is_available(name):
            codec = pa.Codec(name)
            return WireCodec(
                name,
                lambda b: codec.compress(b, asbytes=True),
                lambda b, n: codec.decompress(b, decompressed_size=n,
                                              asbytes=True))
    except Exception:
        pass
    return _zlib_codec(name)


_WIRE_CODECS: Dict[str, Optional[WireCodec]] = {}
_WIRE_CODEC_LOCK = threading.Lock()
_WIRE_CODEC_NAMES = ("lz4", "zstd", "zlib")


def wire_codec(name: Optional[str]) -> Optional[WireCodec]:
    """Resolve a codec name to a WireCodec; None/none/copy disable.
    Only the spec'd names (lz4|zstd|zlib) ever compress — an
    unrecognized name keeps the leg UNCOMPRESSED per the wire format
    doc, never a silent substitution.  A known-but-unavailable codec
    degrades to the stdlib zlib implementation, and the degrade is
    NEVER silent on the wire: a degraded client announces "zlib" in
    its HELLO (negotiated_name), and a degraded server marks every
    frame it compresses with the fallback wrap flag — so availability
    drift between the two processes cannot poison the stream."""
    name = (name or "none").lower()
    if name not in _WIRE_CODEC_NAMES:
        return None
    with _WIRE_CODEC_LOCK:
        if name not in _WIRE_CODECS:
            _WIRE_CODECS[name] = _make_wire_codec(name)
        return _WIRE_CODECS[name]


def encode_data_payload(payload: bytes,
                        codec: Optional[WireCodec]) -> bytes:
    """Wrap one DATA payload for a peer that negotiated a codec; a
    None codec returns the payload untouched (legacy unwrapped leg)."""
    if codec is None:
        return payload
    if payload:
        comp = codec.compress(payload)
        if len(comp) < len(payload):
            # a degraded sender marks its frames: the receiver may
            # hold the NATIVE codec for the negotiated name, and the
            # fallback's zlib bitstream would poison it
            flag = _WIRE_FALLBACK if codec.fallback \
                else _WIRE_COMPRESSED
            return _WIRE_WRAP.pack(flag, len(payload)) + comp
    # empty or incompressible: stored raw, still wrapped so the
    # receiver's framing stays deterministic
    return _WIRE_WRAP.pack(_WIRE_RAW, len(payload)) + payload


def decode_data_payload(payload: bytes, codec: Optional[WireCodec],
                        peer: Optional[str] = None) -> bytes:
    """Inverse of :func:`encode_data_payload`; raises
    ShuffleTransportError on a malformed/corrupted wrapper (surfacing
    as a retryable fetch failure, never silent garbage)."""
    if codec is None:
        return payload
    if len(payload) < _WIRE_WRAP.size:
        raise ShuffleTransportError(
            f"short compressed DATA wrapper ({len(payload)} bytes)",
            peer)
    flag, usize = _WIRE_WRAP.unpack_from(payload, 0)
    body = payload[_WIRE_WRAP.size:]
    if flag == _WIRE_RAW:
        if len(body) != usize:
            raise ShuffleTransportError(
                f"raw DATA wrapper size mismatch ({len(body)} != "
                f"{usize})", peer)
        return body
    if flag not in (_WIRE_COMPRESSED, _WIRE_FALLBACK):
        raise ShuffleTransportError(
            f"unknown DATA wrapper flag {flag}", peer)
    try:
        if flag == _WIRE_FALLBACK:
            # the SENDER's resolution degraded to stdlib zlib:
            # decode with zlib no matter what this end resolved
            out = zlib.decompress(body)
        else:
            out = codec.decompress(body, usize)
    except Exception as e:
        raise ShuffleTransportError(
            f"DATA frame decompression failed ({codec.name}): {e}",
            peer) from e
    if len(out) != usize:
        raise ShuffleTransportError(
            f"decompressed DATA size mismatch ({len(out)} != {usize})",
            peer)
    return out


class ShuffleTransportError(OSError):
    """A socket fault on the shuffle data plane, tagged with the peer
    executor id so callers can distinguish peer death from local bugs.
    Subclasses OSError: existing ``except OSError`` recovery paths keep
    working; new code can catch this type and read ``peer_executor_id``.
    """

    def __init__(self, msg: str, peer_executor_id: Optional[str] = None):
        super().__init__(msg)
        self.peer_executor_id = peer_executor_id

    def __str__(self) -> str:
        base = super().__str__()
        if self.peer_executor_id:
            return f"[peer {self.peer_executor_id}] {base}"
        return base


class _IdleTimeout(Exception):
    """Read timed out on a frame boundary (no bytes consumed): benign on
    a connection with nothing in flight, fatal otherwise."""


def _send_frame(sock: socket.socket, kind: int, tag: int,
                payload: bytes, lock: threading.Lock,
                peer: Optional[str] = None) -> None:
    try:
        with lock:
            sock.sendall(_HDR.pack(kind, tag, len(payload)))
            if payload:
                sock.sendall(payload)
    except ShuffleTransportError:
        raise
    except OSError as e:
        raise ShuffleTransportError(f"send failed: {e}", peer) from e


def _recv_exact(sock: socket.socket, n: int,
                idle_ok: bool = False) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            if idle_ok and not buf:
                raise _IdleTimeout() from None
            # bytes already consumed: resuming would desync the framing
            raise ShuffleTransportError(
                f"read timed out mid-frame ({len(buf)}/{n} bytes)") \
                from None
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _read_frame(sock: socket.socket, peer: Optional[str] = None,
                idle_ok: bool = False
                ) -> Optional[Tuple[int, int, bytes]]:
    try:
        hdr = _recv_exact(sock, _HDR.size, idle_ok=idle_ok)
        if hdr is None:
            return None
        kind, tag, ln = _HDR.unpack(hdr)
        payload = _recv_exact(sock, ln) if ln else b""
    except (ShuffleTransportError, _IdleTimeout):
        raise
    except OSError as e:
        raise ShuffleTransportError(f"read failed: {e}", peer) from e
    if ln and payload is None:
        return None
    return kind, tag, payload


class TcpClientConnection(ClientConnection):
    """Reducer-side connection to one mapper executor over one socket.

    ``read_timeout_s`` arms a watchdog: a read timeout while requests or
    tagged receives are in flight fails them all (a retryable fetch
    failure); an idle-connection timeout is benign and just re-arms.
    """

    def __init__(self, local_executor_id: str, host: str, port: int,
                 peer_executor_id: Optional[str] = None,
                 connect_timeout_s: float = 30.0,
                 read_timeout_s: Optional[float] = None,
                 data_codec: Optional[str] = None):
        self.local_executor_id = local_executor_id
        self.peer_executor_id = peer_executor_id
        self.channel = _TagChannel()
        self._sock = socket.create_connection(
            (host, port), timeout=connect_timeout_s)
        self._read_timeout_s = read_timeout_s or None
        self._sock.settimeout(self._read_timeout_s)
        self._wlock = threading.Lock()
        self._reqs: Dict[int, Transaction] = {}
        self._req_lock = threading.Lock()
        self._next_req = 0
        self._closed = False
        # per-frame DATA codec negotiation: announce the codec in the
        # HELLO suffix; the server wraps every DATA payload back to us
        # (see module header).  None keeps the legacy unwrapped leg.
        self._data_codec = wire_codec(data_codec)
        hello = local_executor_id
        if self._data_codec is not None:
            # a degraded resolution announces "zlib" — negotiating a
            # name this end cannot actually decode natively would
            # split-brain the stream if the server CAN (its native
            # frames would hit our stdlib fallback)
            hello += "\0" + negotiated_name(self._data_codec)
        # per-exchange stats attribution: the reader is a daemon thread
        # that outlives the dialing frame, so it carries the dialer's
        # scope explicitly (faults.StatsScope)
        self._stats_scope = faults.current_scope()
        _send_frame(self._sock, _HELLO, 0, hello.encode(), self._wlock,
                    peer=peer_executor_id)
        self._reader = threading.Thread(target=self._read_loop_scoped,
                                        daemon=True)
        self._reader.start()

    def _read_loop_scoped(self) -> None:
        with faults.attribute_to(self._stats_scope):
            self._read_loop()

    def _has_pending(self) -> bool:
        with self._req_lock:
            if self._reqs:
                return True
        return self.channel.has_pending_recvs()

    def _read_loop(self) -> None:
        # the recv timer starts at re-arm, not when an operation is
        # posted — an op issued late in an idle window would otherwise
        # get an arbitrarily small budget.  Two consecutive expiries
        # with pending ops guarantee every op at least one full window.
        pending_strikes = 0
        while True:
            try:
                frame = _read_frame(self._sock,
                                    peer=self.peer_executor_id,
                                    idle_ok=True)
            except _IdleTimeout:
                if not self._has_pending():
                    pending_strikes = 0
                    continue  # idle connection: benign, keep listening
                pending_strikes += 1
                if pending_strikes < 2:
                    continue
                faults.get_fault_stats().incr("timeouts")
                self._fail_all(
                    f"read timeout after {2 * self._read_timeout_s}s "
                    "with in-flight operations")
                self.close()
                return
            except OSError as e:
                # keep the typed diagnostics (peer id, mid-frame
                # timeout) instead of a generic "connection closed"
                self._fail_all(f"connection error: {e}")
                return
            if frame is None:
                self._fail_all("connection closed")
                return
            pending_strikes = 0  # byte progress: re-arm the watchdog
            kind, tag, payload = frame
            if kind == _DATA:
                plan = faults.get_fault_plan()
                ev = plan.check("tcp.client.data") if plan else None
                if ev is not None:
                    if ev.action == faults.FaultAction.DROP:
                        continue
                    if ev.action == faults.FaultAction.CLOSE:
                        self._fail_all("fault injected: client close")
                        self.close()
                        return
                    if ev.action == faults.FaultAction.CORRUPT:
                        payload = faults.FaultPlan.corrupt(payload)
                    elif ev.action == faults.FaultAction.DELAY:
                        time.sleep(ev.delay_s)
            if kind == _RESP:
                with self._req_lock:
                    tx = self._reqs.pop(tag, None)
                if tx is not None:
                    tx.complete(TransactionStatus.SUCCESS,
                                payload=payload)
            elif kind == _ERR:
                with self._req_lock:
                    tx = self._reqs.pop(tag, None)
                if tx is not None:
                    tx.complete(TransactionStatus.ERROR,
                                error=payload.decode(errors="replace"))
            elif kind == _DATA:
                if self._data_codec is not None:
                    from spark_rapids_tpu.obs import registry as obsreg
                    wire_len = len(payload)
                    try:
                        # fault injection (above) ran on the WIRE bytes,
                        # so a CORRUPT event lands here as a decode
                        # failure — a retryable fetch fault, not garbage
                        payload = decode_data_payload(
                            payload, self._data_codec,
                            peer=self.peer_executor_id)
                    except ShuffleTransportError as e:
                        self._fail_all(f"bad DATA frame: {e}")
                        self.close()
                        return
                    obsreg.get_registry().inc_many(
                        ("shuffle.wire.wireBytes", wire_len),
                        ("shuffle.wire.rawBytes", len(payload)),
                        ("shuffle.wire.frames", 1),
                        ("shuffle.wire.compressedFrames",
                         1 if wire_len < len(payload) +
                         _WIRE_WRAP.size else 0))
                    # tenant ledger, same n as the global counter (the
                    # receive thread usually carries no query token, so
                    # this typically bills "(unattributed)" — counted,
                    # never lost)
                    from spark_rapids_tpu.obs import accounting as _acct
                    _acct.charge("shuffle.wire.rawBytes", len(payload))
                # post as a "send" into the rendezvous; a dummy tx
                # carries the completion the channel requires
                stx = Transaction(tag)
                stx.start(None)
                self.channel.send(tag, payload, stx)

    def _fail_all(self, msg: str) -> None:
        tag = f"[peer {self.peer_executor_id}]"
        if self.peer_executor_id and tag not in msg:
            msg = f"{tag} {msg}"
        with self._req_lock:
            self._closed = True
            pending = list(self._reqs.values())
            self._reqs.clear()
        for tx in pending:
            tx.complete(TransactionStatus.ERROR, error=msg)
        # posted tagged receives must fail too, or a mid-transfer
        # disconnect stalls the iterator until its timeout
        self.channel.fail_all(msg)

    def request(self, data: bytes, cb) -> Transaction:
        tx = Transaction()
        tx.start(cb)
        with self._req_lock:
            if self._closed:
                closed = True
            else:
                closed = False
                rid = self._next_req
                self._next_req += 1
                self._reqs[rid] = tx
        if closed:
            tx.complete(TransactionStatus.ERROR,
                        error="connection closed")
            return tx
        try:
            _send_frame(self._sock, _REQ, rid, data, self._wlock,
                        peer=self.peer_executor_id)
        except OSError as e:
            with self._req_lock:
                self._reqs.pop(rid, None)
            tx.complete(TransactionStatus.ERROR, error=str(e))
        return tx

    def receive(self, tag: int, nbytes: int, cb) -> Transaction:
        tx = Transaction(tag)
        tx.start(cb)
        if self._closed:
            tx.complete(TransactionStatus.ERROR,
                        error="connection closed")
            return tx
        self.channel.receive(tag, nbytes, tx)
        return tx

    def discard_tag_range(self, lo: int, hi: int) -> None:
        self.channel.discard_tag_range(lo, hi)

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class _DeadClientConnection(ClientConnection):
    """Returned when a (re)connect fails: every operation completes with
    ERROR so the client/iterator state machines surface fetch-failed —
    connection failures are data-plane errors, not caller crashes."""

    def __init__(self, msg: str):
        self._msg = msg
        self.closed = True

    def request(self, data: bytes, cb) -> Transaction:
        tx = Transaction()
        tx.start(cb)
        tx.complete(TransactionStatus.ERROR, error=self._msg)
        return tx

    def receive(self, tag: int, nbytes: int, cb) -> Transaction:
        tx = Transaction(tag)
        tx.start(cb)
        tx.complete(TransactionStatus.ERROR, error=self._msg)
        return tx

    def close(self) -> None:
        pass


class TcpServerConnection(ServerConnection):
    """Mapper-side listener: accepts client sockets, routes REQ frames to
    the handler, streams DATA frames back over the requester's socket."""

    def __init__(self, executor_id: str, port: int = 0):
        self.executor_id = executor_id
        self.handler: Optional[Callable] = None
        # peer id -> (socket, write lock, negotiated DATA codec)
        self._peers: Dict[str, Tuple[socket.socket, threading.Lock,
                                     Optional[WireCodec]]] = {}
        self._peer_lock = threading.Lock()
        self._accepted: List[socket.socket] = []
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", port))
        self._lsock.listen(64)
        self.port = self._lsock.getsockname()[1]
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def register_request_handler(self, handler) -> None:
        self.handler = handler

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _addr = self._lsock.accept()
            except OSError:
                return
            with self._peer_lock:
                self._accepted.append(sock)
            threading.Thread(target=self._serve_conn, args=(sock,),
                             daemon=True).start()

    def _serve_conn(self, sock: socket.socket) -> None:
        wlock = threading.Lock()
        peer_id: Optional[str] = None
        try:
            while True:
                try:
                    frame = _read_frame(sock)
                except OSError:
                    frame = None
                if frame is None:
                    return
                kind, tag, payload = frame
                if kind == _HELLO:
                    # "executor_id" or "executor_id\0codec": a codec
                    # suffix negotiates per-frame DATA compression —
                    # every DATA payload to this peer is then wrapped.
                    # If OUR resolution of the announced name degrades
                    # to the zlib fallback, the frames we compress are
                    # flag-marked so the (possibly native) client
                    # still decodes them correctly.
                    text = payload.decode()
                    peer_id, _, codec_name = text.partition("\0")
                    codec = wire_codec(codec_name or None)
                    with self._peer_lock:
                        self._peers[peer_id] = (sock, wlock, codec)
                elif kind == _REQ and self.handler is not None:
                    try:
                        resp_kind, resp = _RESP, self.handler(
                            payload, peer_id or "")
                    except Exception as e:  # surfaced as transport error
                        resp_kind, resp = _ERR, str(e).encode()
                    try:
                        _send_frame(sock, resp_kind, tag, resp or b"",
                                    wlock)
                    except OSError:
                        return
        finally:
            # every exit path: drop our peer entry (a reconnect may have
            # registered a NEWER socket under this id — only drop our
            # own), close the fd, and prune the accepted list
            with self._peer_lock:
                if peer_id is not None:
                    cur = self._peers.get(peer_id)
                    if cur is not None and cur[0] is sock:
                        self._peers.pop(peer_id, None)
                try:
                    self._accepted.remove(sock)
                except ValueError:
                    pass
            try:
                sock.close()
            except OSError:
                pass

    def send(self, peer_executor_id: str, tag: int, data: bytes,
             cb) -> Transaction:
        tx = Transaction(tag)
        tx.start(cb)
        with self._peer_lock:
            peer = self._peers.get(peer_executor_id)
        if peer is None:
            tx.complete(TransactionStatus.ERROR,
                        error=f"no connection from {peer_executor_id}")
            return tx
        sock, wlock, codec = peer
        raw_len = len(data)
        if codec is not None:
            data = encode_data_payload(data, codec)
        plan = faults.get_fault_plan()
        ev = plan.check("tcp.server.data") if plan else None
        if ev is not None:
            if ev.action == faults.FaultAction.DROP:
                # frame silently lost: the stream keeps going, leaving a
                # hole the receiver must detect and re-fetch
                tx.complete(TransactionStatus.SUCCESS)
                return tx
            if ev.action == faults.FaultAction.CLOSE:
                try:
                    sock.close()  # peer sees a mid-window disconnect
                except OSError:
                    pass
            elif ev.action == faults.FaultAction.CORRUPT:
                data = faults.FaultPlan.corrupt(data)
            elif ev.action == faults.FaultAction.DELAY:
                time.sleep(ev.delay_s)
        try:
            _send_frame(sock, _DATA, tag, data, wlock,
                        peer=peer_executor_id)
            if codec is not None:
                # counted only AFTER the frame actually hit the wire:
                # dropped / failed sends must not inflate the
                # serving-side savings audit
                from spark_rapids_tpu.obs import registry as obsreg
                obsreg.get_registry().inc_many(
                    ("shuffle.wire.sentWireBytes", len(data)),
                    ("shuffle.wire.sentRawBytes", raw_len))
            tx.complete(TransactionStatus.SUCCESS)
        except OSError as e:
            tx.complete(TransactionStatus.ERROR, error=str(e))
        return tx

    def close(self) -> None:
        try:
            self._lsock.close()
        except OSError:
            pass
        with self._peer_lock:
            accepted, self._accepted = self._accepted, []
            self._peers.clear()
        for sock in accepted:
            try:
                sock.close()
            except OSError:
                pass


class TcpShuffleTransport(ShuffleTransport):
    """Socket transport loadable via ``make_transport`` exactly like the
    reference's UCX plugin (RapidsShuffleTransport.scala:542-576).

    conf (dict or RapidsTpuConf-like with ``.get``):
      * ``listen_port``: server bind port (default 0 = ephemeral)
      * ``peers``: {executor_id: (host, port)} address book; entries can
        be added later via ``add_peer`` (the analog of discovering a
        peer's port from MapStatus topology)
      * ``connect_timeout_ms`` (default 30000) / ``read_timeout_ms``
        (default 10000, 0 disables): per-socket timeouts
      * ``connect_max_retries`` (default 2) / ``connect_backoff_ms``
        (default 50): bounded reconnect with exponential backoff +
        deterministic jitter (``seed``, default 0)
      * ``data_codec`` (default "none"): per-frame DATA compression
        codec this transport's clients negotiate in their HELLO
        (lz4 | zstd | zlib); the serving side wraps every DATA payload
        to a negotiating peer — see the module-header wrap layout
    """

    def __init__(self, executor_id: str, conf=None):
        super().__init__(executor_id, conf)
        conf = conf or {}
        get = conf.get if hasattr(conf, "get") else lambda k, d=None: d
        self._peers: Dict[str, Tuple[str, int]] = dict(
            get("peers", {}) or {})
        self._listen_port = int(get("listen_port", 0) or 0)
        self._connect_timeout_s = float(
            get("connect_timeout_ms", 30_000) or 30_000) / 1000.0
        self._read_timeout_s = float(
            get("read_timeout_ms", 10_000) or 0) / 1000.0
        self._connect_retries = int(get("connect_max_retries", 2) or 0)
        self._backoff_s = float(
            get("connect_backoff_ms", 50) or 50) / 1000.0
        # per-frame DATA codec this transport's clients negotiate in
        # their HELLO ("none" disables; see wire_codec)
        self._data_codec = str(get("data_codec", "none") or "none")
        self._rng = random.Random(int(get("seed", 0) or 0))
        self._server: Optional[TcpServerConnection] = None
        self._clients: Dict[str, TcpClientConnection] = {}
        self._clients_lock = threading.Lock()
        self._dial_locks: Dict[str, threading.Lock] = {}
        # peer -> (monotonic stamp, error) of the most recent failed
        # dial: waiters that were already queued behind the dial lock
        # when it failed share the outcome instead of each paying the
        # full connect ladder against the same dead address
        self._dial_failures: Dict[str, Tuple[float, str]] = {}

    def add_peer(self, executor_id: str, host: str, port: int) -> None:
        self._peers[executor_id] = (host, port)

    def _connect(self, peer_executor_id: str, host: str,
                 port: int) -> TcpClientConnection:
        """Bounded reconnect: exponential backoff + jitter per attempt
        (the reference's UCX mgmt-connection retry loop analog)."""
        from spark_rapids_tpu.shuffle.transport import backoff_delay_s
        stats = faults.get_fault_stats()
        last: Optional[OSError] = None
        for attempt in range(self._connect_retries + 1):
            if attempt:
                time.sleep(backoff_delay_s(self._backoff_s, attempt,
                                           self._rng))
                stats.incr("reconnects")
            plan = faults.get_fault_plan()
            ev = plan.check("tcp.connect") if plan else None
            if ev is not None and ev.action in (faults.FaultAction.CLOSE,
                                                faults.FaultAction.DROP):
                last = ShuffleTransportError(
                    "fault injected: connect refused", peer_executor_id)
                continue
            try:
                return TcpClientConnection(
                    self.executor_id, host, port,
                    peer_executor_id=peer_executor_id,
                    connect_timeout_s=self._connect_timeout_s,
                    read_timeout_s=self._read_timeout_s or None,
                    data_codec=self._data_codec)
            except OSError as e:
                last = e
        raise ShuffleTransportError(
            f"connect to {peer_executor_id} at {host}:{port} failed "
            f"after {self._connect_retries + 1} attempts: {last}",
            peer_executor_id)

    def make_client(self, peer_executor_id: str) -> ClientConnection:
        # Dials to the SAME peer are serialized by a per-peer lock: two
        # threads racing make_client would otherwise both connect, and
        # closing the losing socket is NOT harmless — the server keys
        # its DATA routing by the client's executor id, so the loser's
        # HELLO clobbers the winner's peer entry and the loser's close
        # then drops the entry entirely, leaving every subsequent DATA
        # frame unroutable (a silent fetch stall until the read
        # watchdog).  Exactly one live connection per (local, peer)
        # pair may ever exist.  Dials to DIFFERENT peers still run
        # concurrently — a dead peer's connect timeouts serialize only
        # its own callers, never the fleet.
        t_enter = time.monotonic()
        with self._clients_lock:
            cached = self._clients.get(peer_executor_id)
            if cached is not None and not cached.closed:
                return cached
            dial_lock = self._dial_locks.setdefault(
                peer_executor_id, threading.Lock())
        with dial_lock:
            with self._clients_lock:
                cached = self._clients.get(peer_executor_id)
                if cached is not None:
                    if not cached.closed:
                        return cached  # a queued dialer's work arrived
                    # dead connection (peer restarted / network drop):
                    # reconnect to the current address book entry
                    cached.close()
                    del self._clients[peer_executor_id]
                    faults.get_fault_stats().incr("reconnects")
                failed = self._dial_failures.get(peer_executor_id)
                if failed is not None and failed[0] > t_enter:
                    # a dial that ran WHILE we queued just failed:
                    # share its outcome rather than stacking another
                    # full connect ladder behind the same dead
                    # address (k waiters would otherwise serialize
                    # k timeouts).  Callers entering AFTER the
                    # failure — e.g. retries following an add_peer
                    # repoint — dial fresh.
                    return _DeadClientConnection(failed[1])
            if peer_executor_id not in self._peers:
                raise KeyError(f"unknown peer {peer_executor_id}; "
                               f"add_peer() or conf['peers'] required")
            host, port = self._peers[peer_executor_id]
            try:
                # dialing (timeouts + backoff sleeps) happens outside
                # the cache lock; the per-peer dial lock holds
                c = self._connect(peer_executor_id, host, port)
            except OSError as e:
                # do NOT cache a dead connection: the next make_client
                # retries the connect — but stamp the failure so
                # already-queued waiters share it (above)
                with self._clients_lock:
                    self._dial_failures[peer_executor_id] = (
                        time.monotonic(), str(e))
                return _DeadClientConnection(str(e))
            with self._clients_lock:
                self._clients[peer_executor_id] = c
                self._dial_failures.pop(peer_executor_id, None)
            return c

    def server(self) -> TcpServerConnection:
        if self._server is None:
            self._server = TcpServerConnection(self.executor_id,
                                               self._listen_port)
        return self._server

    def shutdown(self) -> None:
        for c in self._clients.values():
            c.close()
        self._clients.clear()
        if self._server is not None:
            self._server.close()
            self._server = None
