"""Accelerated-shuffle server: serves metadata + streams buffers.

Reference analog (SURVEY.md §2f): ``RapidsShuffleServer.scala:71-446`` —
``doHandleTransferRequest`` (:368) streams requested buffers through send
bounce buffers via ``BufferSendState`` (BufferSendState.scala:236), which
windows many blocks through a fixed staging buffer.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.shuffle import meta as wire
from spark_rapids_tpu.shuffle.catalogs import ShuffleBufferCatalog
from spark_rapids_tpu.shuffle.transport import (BounceBufferManager,
                                                ServerConnection,
                                                Transaction,
                                                TransactionStatus,
                                                WindowedBlockIterator)


class BufferSendState:
    """Walks the requested buffers' payloads window-by-window through one
    send bounce buffer (reference: BufferSendState.scala:236).  Each
    ``next_window`` returns the exact bytes for one window; the server
    sends them tagged and ordered."""

    def __init__(self, payloads: List[bytes], window_size: int,
                 bounce_mgr: Optional[BounceBufferManager] = None):
        self.payloads = payloads
        self.window_size = window_size
        self._iter = WindowedBlockIterator([len(p) for p in payloads],
                                           window_size)
        self._bounce_mgr = bounce_mgr
        self.windows_sent = 0
        self.bytes_sent = 0

    def has_next(self) -> bool:
        return self._iter.has_next()

    def next_window(self) -> bytes:
        ranges = next(self._iter)
        bounce = (self._bounce_mgr.acquire() if self._bounce_mgr else None)
        try:
            out = bytearray()
            for r in ranges:
                out += self.payloads[r.block][
                    r.range_start:r.range_start + r.range_size]
            self.windows_sent += 1
            self.bytes_sent += len(out)
            return bytes(out)
        finally:
            if bounce is not None:
                bounce.close()


class ShuffleServer:
    """Handles MetadataRequest / TransferRequest control frames."""

    def __init__(self, executor_id: str, catalog: ShuffleBufferCatalog,
                 connection: ServerConnection,
                 send_bounce: Optional[BounceBufferManager] = None):
        self.executor_id = executor_id
        self.catalog = catalog
        self.connection = connection
        self.send_bounce = send_bounce
        connection.register_request_handler(self.handle_request)

    # -- control-frame dispatch -------------------------------------------
    def handle_request(self, data: bytes, peer_executor_id: str) -> bytes:
        import struct
        (_, _, ftype) = struct.unpack_from("<IHH", data, 0)
        if ftype == wire.FRAME_META_REQ:
            return self._handle_metadata(wire.MetadataRequest.unpack(data))
        if ftype == wire.FRAME_XFER_REQ:
            return self._handle_transfer(
                wire.TransferRequest.unpack(data), peer_executor_id)
        raise ValueError(f"unknown frame type {ftype}")

    def _handle_metadata(self, req: wire.MetadataRequest) -> bytes:
        blocks = self.catalog.blocks_for(req.shuffle_id, req.reduce_id,
                                         req.map_ids or None)
        return wire.MetadataResponse([b.table_meta for b in blocks]).pack()

    def _handle_transfer(self, req: wire.TransferRequest,
                         peer_executor_id: str) -> bytes:
        """doHandleTransferRequest analog
        (RapidsShuffleServer.scala:368): materialize payloads (unspilling
        if needed), stream windows to the peer's tagged receives."""
        try:
            payloads = [self.catalog.block_payload(bid)
                        for bid in req.buffer_ids]
        except KeyError:
            return wire.TransferResponse(error_code=1).pack()
        state = BufferSendState(payloads, req.window_size, self.send_bounce)

        def send_next(_tx: Optional[Transaction]) -> None:
            if _tx is not None and _tx.status != TransactionStatus.SUCCESS:
                return  # receiver vanished; stop streaming
            if not state.has_next():
                return
            # window i moves under tag receive_tag+i (the receiver posts
            # the same sequence): a lost window is a detectable hole,
            # never a silent misalignment of later windows
            wtag = req.receive_tag + state.windows_sent
            data = state.next_window()
            obsreg.get_registry().inc_many(
                ("shuffle.serveBytes", len(data)),
                ("shuffle.serveFrames", 1))
            self.connection.send(peer_executor_id, wtag,
                                 data, send_next)

        # kick off the stream; subsequent windows chain off completions
        send_next(None)
        return wire.TransferResponse(error_code=0).pack()
