"""Shuffle wire metadata: table/column/buffer descriptors + control frames.

Reference analog (SURVEY.md §2f): the FlatBuffers schemas in
``sql-plugin/src/main/format/*.fbs`` (ShuffleMetadata request/response,
TableMeta/ColumnMeta/BufferMeta, codec descriptors, TransferRequest/
TransferResponse) and their builder/parser ``MetaUtils.scala:33-527``,
including degenerate (0-row / 0-col) batch metadata
(``MetaUtils.buildDegenerateTableMeta`` MetaUtils.scala:145).

The encoding here is a versioned little-endian struct layout rather than
FlatBuffers (the flatbuffers runtime is not in this image); it is
language-neutral and self-describing the same way — a C++ peer can parse
it with a 40-line reader.  All multi-byte fields are ``<`` little-endian.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import List, Optional

MAGIC = 0x54505553  # "TPUS"
VERSION = 1

# codec ids on the wire (reference: CodecType in ShuffleMetadata fbs)
CODEC_UNCOMPRESSED = 0
CODEC_COPY = 1
CODEC_LZ4 = 2
CODEC_ZSTD = 3

_CODEC_NAMES = {CODEC_UNCOMPRESSED: "none", CODEC_COPY: "copy",
                CODEC_LZ4: "lz4", CODEC_ZSTD: "zstd"}
_CODEC_IDS = {v: k for k, v in _CODEC_NAMES.items()}
# conf value "zlib" compresses only the TCP wire leg; blocks
# serialize uncompressed (Arrow IPC has no zlib buffer compression),
# so their metadata carries the uncompressed id
_CODEC_IDS["zlib"] = CODEC_UNCOMPRESSED


def codec_name(codec_id: int) -> str:
    return _CODEC_NAMES[codec_id]


def codec_id(name: str) -> int:
    return _CODEC_IDS[name]


def _pack_str(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack("<I", len(b)) + b


def _unpack_str(buf: memoryview, off: int):
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    return bytes(buf[off:off + n]).decode("utf-8"), off + n


@dataclass
class ColumnMeta:
    """Per-column descriptor (reference: ColumnMeta table in the fbs)."""
    name: str
    dtype_code: str       # spark_rapids_tpu.dtypes code, e.g. "int32"
    nullable: bool
    null_count: int

    def pack(self) -> bytes:
        return (_pack_str(self.name) + _pack_str(self.dtype_code) +
                struct.pack("<BQ", int(self.nullable), self.null_count))

    @staticmethod
    def unpack(buf: memoryview, off: int):
        name, off = _unpack_str(buf, off)
        code, off = _unpack_str(buf, off)
        nullable, null_count = struct.unpack_from("<BQ", buf, off)
        return ColumnMeta(name, code, bool(nullable), null_count), off + 9


@dataclass
class BufferMeta:
    """Physical buffer descriptor (reference: BufferMeta in the fbs):
    identity + codec + sizes, enough for the receiver to size its bounce
    windows and decompress."""
    buffer_id: int
    uncompressed_size: int
    compressed_size: int
    codec: int = CODEC_UNCOMPRESSED

    def pack(self) -> bytes:
        return struct.pack("<QQQI", self.buffer_id, self.uncompressed_size,
                           self.compressed_size, self.codec)

    @staticmethod
    def unpack(buf: memoryview, off: int):
        bid, usz, csz, codec = struct.unpack_from("<QQQI", buf, off)
        return BufferMeta(bid, usz, csz, codec), off + 28


@dataclass
class TableMeta:
    """One shuffle block = one table (reference: TableMeta, built by
    MetaUtils.buildTableMeta MetaUtils.scala:48).  ``buffer_meta`` is None
    for degenerate batches (0 rows or 0 columns), which ship as metadata
    only (MetaUtils.scala:145)."""
    num_rows: int
    columns: List[ColumnMeta]
    buffer_meta: Optional[BufferMeta]

    @property
    def is_degenerate(self) -> bool:
        return self.buffer_meta is None

    def pack(self) -> bytes:
        out = [struct.pack("<QI", self.num_rows, len(self.columns))]
        out += [c.pack() for c in self.columns]
        if self.buffer_meta is None:
            out.append(struct.pack("<B", 0))
        else:
            out.append(struct.pack("<B", 1))
            out.append(self.buffer_meta.pack())
        return b"".join(out)

    @staticmethod
    def unpack(buf: memoryview, off: int):
        num_rows, ncols = struct.unpack_from("<QI", buf, off)
        off += 12
        cols = []
        for _ in range(ncols):
            c, off = ColumnMeta.unpack(buf, off)
            cols.append(c)
        (has_buf,) = struct.unpack_from("<B", buf, off)
        off += 1
        bm = None
        if has_buf:
            bm, off = BufferMeta.unpack(buf, off)
        return TableMeta(num_rows, cols, bm), off


# ---------------------------------------------------------------------------
# Control frames (reference: MetadataRequest/MetadataResponse,
# TransferRequest/TransferResponse tables in ShuffleMetadata.fbs)
# ---------------------------------------------------------------------------

FRAME_META_REQ = 1
FRAME_META_RESP = 2
FRAME_XFER_REQ = 3
FRAME_XFER_RESP = 4


def _header(frame_type: int) -> bytes:
    return struct.pack("<IHH", MAGIC, VERSION, frame_type)


def _check_header(buf: memoryview, expect: int) -> int:
    magic, version, ftype = struct.unpack_from("<IHH", buf, 0)
    if magic != MAGIC:
        raise ValueError(f"bad magic 0x{magic:x}")
    if version != VERSION:
        raise ValueError(f"unsupported wire version {version}")
    if ftype != expect:
        raise ValueError(f"expected frame {expect}, got {ftype}")
    return 8


@dataclass
class MetadataRequest:
    """Reducer asks a mapper executor for TableMetas of its blocks."""
    shuffle_id: int
    reduce_id: int
    map_ids: List[int] = field(default_factory=list)

    def pack(self) -> bytes:
        out = [_header(FRAME_META_REQ),
               struct.pack("<III", self.shuffle_id, self.reduce_id,
                           len(self.map_ids))]
        out += [struct.pack("<I", m) for m in self.map_ids]
        return b"".join(out)

    @staticmethod
    def unpack(data: bytes) -> "MetadataRequest":
        buf = memoryview(data)
        off = _check_header(buf, FRAME_META_REQ)
        sid, rid, n = struct.unpack_from("<III", buf, off)
        off += 12
        maps = list(struct.unpack_from(f"<{n}I", buf, off)) if n else []
        return MetadataRequest(sid, rid, maps)


@dataclass
class MetadataResponse:
    tables: List[TableMeta]

    def pack(self) -> bytes:
        out = [_header(FRAME_META_RESP), struct.pack("<I", len(self.tables))]
        out += [t.pack() for t in self.tables]
        return b"".join(out)

    @staticmethod
    def unpack(data: bytes) -> "MetadataResponse":
        buf = memoryview(data)
        off = _check_header(buf, FRAME_META_RESP)
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        tables = []
        for _ in range(n):
            t, off = TableMeta.unpack(buf, off)
            tables.append(t)
        return MetadataResponse(tables)


@dataclass
class TransferRequest:
    """Reducer asks the server to stream these buffers to its receive tag
    (reference: TransferRequest with per-buffer tags).  ``window_size``
    is the bounce-window both sides iterate with, so the sender's
    BufferSendState and the receiver's BufferReceiveState walk identical
    WindowedBlockIterator sequences."""
    receive_tag: int
    window_size: int
    buffer_ids: List[int]

    def pack(self) -> bytes:
        out = [_header(FRAME_XFER_REQ),
               struct.pack("<QQI", self.receive_tag, self.window_size,
                           len(self.buffer_ids))]
        out += [struct.pack("<Q", b) for b in self.buffer_ids]
        return b"".join(out)

    @staticmethod
    def unpack(data: bytes) -> "TransferRequest":
        buf = memoryview(data)
        off = _check_header(buf, FRAME_XFER_REQ)
        tag, window, n = struct.unpack_from("<QQI", buf, off)
        off += 20
        ids = [struct.unpack_from("<Q", buf, off + 8 * i)[0]
               for i in range(n)]
        return TransferRequest(tag, window, ids)


@dataclass
class TransferResponse:
    """Server acknowledges which buffers it will stream (0 = all ok)."""
    error_code: int = 0

    def pack(self) -> bytes:
        return _header(FRAME_XFER_RESP) + struct.pack("<I", self.error_code)

    @staticmethod
    def unpack(data: bytes) -> "TransferResponse":
        buf = memoryview(data)
        off = _check_header(buf, FRAME_XFER_RESP)
        (code,) = struct.unpack_from("<I", buf, off)
        return TransferResponse(code)
