"""Accelerated shuffle manager: executor env, caching writer/reader.

Reference analog (SURVEY.md §2f):
* ``GpuShuffleEnv`` (GpuShuffleEnv.scala:26-108) — executor-singleton
  wiring of catalogs + transport; here ``ShuffleEnv`` plays that role per
  simulated executor.
* ``RapidsCachingWriter`` (RapidsShuffleInternalManager.scala:73-192) —
  map output batches stay in the device store, registered in the
  ShuffleBufferCatalog; the "rapids=<port>" MapStatus topology string
  becomes the executor id carried in ``MapOutputInfo``.
* ``RapidsCachingReader`` (RapidsCachingReader.scala:170) — local blocks
  from the catalog, remote via transport clients, assembled by
  ``RapidsShuffleIterator``.
* ``RapidsShuffleInternalManagerBase`` (:200-374) — falls through to the
  default serialized path when the accelerated manager is disabled (the
  exec layer does that via config).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

import pyarrow as pa

from spark_rapids_tpu.columnar.batch import DeviceBatch
from spark_rapids_tpu.config import (SHUFFLE_COMPRESSION_CODEC,
                                     SHUFFLE_FETCH_MAX_RETRIES,
                                     SHUFFLE_FETCH_RETRY_BACKOFF_MS,
                                     RapidsTpuConf)
from spark_rapids_tpu.shuffle.catalogs import (ShuffleBufferCatalog,
                                               ShuffleReceivedBufferCatalog)
from spark_rapids_tpu.shuffle.client import RapidsShuffleClient
from spark_rapids_tpu.shuffle.iterator import (RapidsShuffleIterator,
                                               RemoteSource)
from spark_rapids_tpu.shuffle.server import ShuffleServer
from spark_rapids_tpu.shuffle.transport import (BounceBufferManager,
                                                InflightLimiter,
                                                ShuffleTransport,
                                                make_transport)


@dataclass
class MapOutputInfo:
    """Which executor holds a map task's output (MapStatus topology
    analog, RapidsShuffleInternalManager.scala:163-186)."""
    shuffle_id: int
    map_id: int
    executor_id: str


class ShuffleEnv:
    """Per-executor shuffle wiring (GpuShuffleEnv analog)."""

    def __init__(self, executor_id: str, conf: RapidsTpuConf,
                 transport: Optional[ShuffleTransport] = None):
        self.executor_id = executor_id
        self.conf = conf
        codec = conf.get(SHUFFLE_COMPRESSION_CODEC)
        self.catalog = ShuffleBufferCatalog(codec_name=codec)
        self.received = ShuffleReceivedBufferCatalog()
        if transport is None:
            transport = make_transport(
                "spark_rapids_tpu.shuffle.local.LocalShuffleTransport",
                executor_id, conf)
        self.transport = transport
        self.send_bounce = BounceBufferManager(
            f"{executor_id}-send", buffer_size=1 << 20, num_buffers=4)
        self.recv_bounce = BounceBufferManager(
            f"{executor_id}-recv", buffer_size=1 << 20, num_buffers=4)
        self.inflight = InflightLimiter(max_bytes=64 << 20)
        self.server = ShuffleServer(executor_id, self.catalog,
                                    transport.server(), self.send_bounce)
        self._clients: Dict[str, RapidsShuffleClient] = {}
        self._lock = threading.Lock()

    def client_for(self, peer_executor_id: str) -> RapidsShuffleClient:
        with self._lock:
            c = self._clients.get(peer_executor_id)
            # a dead connection (peer restarted, network drop) must not
            # pin this peer to permanent failure: rebuild the wrapper so
            # the transport can reconnect (its make_client revalidates)
            if c is not None and getattr(c.connection, "closed", False):
                self._clients.pop(peer_executor_id, None)
                c = None
            if c is None:
                c = RapidsShuffleClient(
                    self.transport.make_client(peer_executor_id),
                    self.received, bounce_window=1 << 20,
                    recv_bounce=self.recv_bounce, inflight=self.inflight)
                self._clients[peer_executor_id] = c
            return c

    def close(self) -> None:
        self.transport.shutdown()


class TpuShuffleManager:
    """Tracks map-output locations across executors and hands out
    writers/readers — the ShuffleManager role, minus Spark's driver."""

    def __init__(self, conf: RapidsTpuConf):
        self.conf = conf
        self._envs: Dict[str, ShuffleEnv] = {}
        self._map_outputs: Dict[int, List[MapOutputInfo]] = {}
        self._shuffle_ids = itertools.count(1)
        self._lock = threading.Lock()

    def register_executor(self, executor_id: str,
                          transport: Optional[ShuffleTransport] = None
                          ) -> ShuffleEnv:
        with self._lock:
            env = self._envs.get(executor_id)
            if env is None:
                env = ShuffleEnv(executor_id, self.conf, transport)
                self._envs[executor_id] = env
            return env

    def new_shuffle_id(self) -> int:
        return next(self._shuffle_ids)

    # -- writer ------------------------------------------------------------
    def write_map_output(self, executor_id: str, shuffle_id: int,
                         map_id: int,
                         partitions: List[Optional[DeviceBatch]]) -> None:
        """RapidsCachingWriter.write analog: one device batch per reduce
        partition stays HBM-resident in the executor's catalog."""
        env = self.register_executor(executor_id)
        for reduce_id, batch in enumerate(partitions):
            if batch is None:
                continue
            env.catalog.register_batch(shuffle_id, map_id, reduce_id, batch)
        with self._lock:
            self._map_outputs.setdefault(shuffle_id, []).append(
                MapOutputInfo(shuffle_id, map_id, executor_id))

    # -- reader ------------------------------------------------------------
    def read_partition(self, executor_id: str, shuffle_id: int,
                       reduce_id: int,
                       timeout_s: float = 30.0) -> Iterator[pa.Table]:
        """RapidsCachingReader analog: local catalog + remote fetches."""
        env = self.register_executor(executor_id)
        with self._lock:
            infos = list(self._map_outputs.get(shuffle_id, []))
        peers: Dict[str, List[int]] = {}
        for info in infos:
            if info.executor_id != executor_id:
                peers.setdefault(info.executor_id, []).append(info.map_id)
        remotes = [RemoteSource(peer, env.client_for(peer), map_ids,
                                refresh=lambda p=peer: env.client_for(p))
                   for peer, map_ids in sorted(peers.items())]
        local = env.catalog if any(
            i.executor_id == executor_id for i in infos) else None
        return iter(RapidsShuffleIterator(
            shuffle_id, reduce_id, local, remotes, env.received,
            timeout_s=timeout_s,
            max_retries=int(self.conf.get(SHUFFLE_FETCH_MAX_RETRIES)),
            retry_backoff_ms=float(
                self.conf.get(SHUFFLE_FETCH_RETRY_BACKOFF_MS))))

    def unregister_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            self._map_outputs.pop(shuffle_id, None)
            envs = list(self._envs.values())
        for env in envs:
            env.catalog.unregister_shuffle(shuffle_id)

    def close(self) -> None:
        with self._lock:
            envs = list(self._envs.values())
            self._envs.clear()
        for env in envs:
            env.close()


_global_manager: Optional[TpuShuffleManager] = None
_global_lock = threading.Lock()


def get_shuffle_manager(conf: RapidsTpuConf) -> TpuShuffleManager:
    """Process-wide manager (the executor-singleton GpuShuffleEnv idiom,
    GpuShuffleEnv.scala:26)."""
    global _global_manager
    with _global_lock:
        if _global_manager is None:
            _global_manager = TpuShuffleManager(conf)
        return _global_manager


def reset_shuffle_manager() -> None:
    global _global_manager
    with _global_lock:
        if _global_manager is not None:
            _global_manager.close()
        _global_manager = None
