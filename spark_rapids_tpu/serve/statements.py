"""Prepared/parameterized statements: plan once, bind values per run.

A ``PreparedStatement`` parses its SQL exactly once (``:name``
placeholders lower to ``SqlParam``-valued Literals of their declared
dtype — sql/parser.py) and keeps the resulting *plan template*.  Each
execution deep-copies the template with the heavyweight leaves shared
(Arrow tables, cached relations, file scans are immutable inputs) and
swaps the markers for the bound values — a pure value substitution:
dtypes, schemas and every downstream type resolution were fixed at
prepare time, so binding can never re-plan.

Because the kernel cache keys on canonical expression signatures
(PR 4's alias dedup), two *different* serve sessions executing the same
prepared statement with the same bindings land on the same compiled
kernels — and, through the result-set cache, on the same materialized
result.
"""

from __future__ import annotations

import copy
import datetime as _dt
import threading
from typing import Any, Dict, List, Optional

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.expr import ir
from spark_rapids_tpu.plan import logical as lp
from spark_rapids_tpu.plan.digest import (iter_node_exprs,
                                          iter_plan_exprs, walk)
from spark_rapids_tpu.sql.parser import SqlParam, parse_prepared

# declared-type names accepted in a prepare request (the CAST name set)
_PARAM_TYPE_NAMES = {
    "boolean": dt.BOOL, "bool": dt.BOOL,
    "tinyint": dt.INT8, "byte": dt.INT8,
    "smallint": dt.INT16, "short": dt.INT16,
    "int": dt.INT32, "integer": dt.INT32,
    "bigint": dt.INT64, "long": dt.INT64,
    "float": dt.FLOAT32, "real": dt.FLOAT32,
    "double": dt.FLOAT64,
    "string": dt.STRING, "varchar": dt.STRING,
    "date": dt.DATE32, "timestamp": dt.TIMESTAMP_US,
}


class StatementError(ValueError):
    """Bad prepare/bind input (unknown type, missing/mistyped value)."""


def resolve_param_types(declared: Optional[Dict[str, str]]
                        ) -> Dict[str, dt.DType]:
    out: Dict[str, dt.DType] = {}
    for name, tyname in (declared or {}).items():
        ty = _PARAM_TYPE_NAMES.get(str(tyname).strip().lower())
        if ty is None:
            raise StatementError(
                f"parameter :{name}: unknown type {tyname!r} "
                f"(expected one of {sorted(set(_PARAM_TYPE_NAMES))})")
        out[name] = ty
    return out


def copy_plan_shared_leaves(plan: lp.LogicalPlan) -> lp.LogicalPlan:
    """Deep-copy a plan tree sharing the immutable heavyweight leaves:
    scan nodes (their Arrow tables / path lists never change under
    binding — parameters live in the statement's own operators, never
    inside a catalog relation) and materialized cache nodes (a copy
    would silently re-materialize per execution)."""
    memo: Dict[int, Any] = {}
    for node in walk(plan):
        if not node.children or isinstance(node, lp.CachedRelation):
            memo[id(node)] = node
    return copy.deepcopy(plan, memo)


def _coerce(name: str, value: Any, dtype: dt.DType) -> Any:
    """Validate/convert one JSON-transported binding to its declared
    dtype's python literal form."""
    if value is None:
        return None
    try:
        if dtype == dt.BOOL:
            if isinstance(value, bool):
                return value
            raise TypeError("expected bool")
        if dtype.is_integral:
            if isinstance(value, bool) or not isinstance(value, int):
                raise TypeError("expected int")
            return int(value)
        if dtype.is_floating:
            if isinstance(value, bool) or \
                    not isinstance(value, (int, float)):
                raise TypeError("expected number")
            return float(value)
        if dtype == dt.STRING:
            if not isinstance(value, str):
                raise TypeError("expected string")
            return value
        if dtype == dt.DATE32:
            if isinstance(value, _dt.date) and \
                    not isinstance(value, _dt.datetime):
                return value
            return _dt.date.fromisoformat(str(value))
        if dtype == dt.TIMESTAMP_US:
            if isinstance(value, _dt.datetime):
                v = value
            else:
                v = _dt.datetime.fromisoformat(str(value))
            if v.tzinfo is None:
                v = v.replace(tzinfo=_dt.timezone.utc)
            return v
    except (TypeError, ValueError) as e:
        raise StatementError(
            f"parameter :{name}: cannot bind {value!r} as "
            f"{dtype.name}: {e}") from None
    raise StatementError(
        f"parameter :{name}: unsupported parameter dtype {dtype.name}")


class PreparedStatement:
    """One parsed statement template + its parameter declarations."""

    def __init__(self, statement_id: str, sql: str,
                 declared_types: Optional[Dict[str, str]], catalog):
        self.statement_id = statement_id
        self.sql = sql
        self.declared_types = {str(k): str(v) for k, v in
                               (declared_types or {}).items()}
        self.param_types = resolve_param_types(declared_types)
        self.plan_template, self.params_used = parse_prepared(
            sql, catalog, self.param_types)
        self._lock = threading.Lock()
        self.executions = 0

    @property
    def schema_names(self) -> List[str]:
        return list(self.plan_template.schema.names)

    def describe(self) -> Dict[str, Any]:
        return {
            "statement_id": self.statement_id,
            "columns": self.schema_names,
            "params": {n: t.name for n, t in self.params_used.items()},
            # the original text + declared types ride along so a client
            # that lost its session (drain, replica swap) can replay
            # the prepare verbatim against the re-attached session
            "sql": self.sql,
            "declared_types": dict(self.declared_types),
        }

    def bind(self, params: Optional[Dict[str, Any]]) -> lp.LogicalPlan:
        """A fresh executable plan with every SqlParam marker replaced
        by its bound (coerced) value.  Missing or surplus bindings are
        errors — a silently unbound marker would reach a kernel."""
        params = dict(params or {})
        missing = sorted(set(self.params_used) - set(params))
        if missing:
            raise StatementError(
                f"statement {self.statement_id}: missing bindings for "
                f"{', '.join(':' + m for m in missing)}")
        surplus = sorted(set(params) - set(self.params_used))
        if surplus:
            raise StatementError(
                f"statement {self.statement_id}: unknown parameters "
                f"{', '.join(':' + s for s in surplus)}")
        coerced = {n: _coerce(n, params[n], self.params_used[n])
                   for n in self.params_used}
        plan = copy_plan_shared_leaves(self.plan_template)
        bound = 0
        for root in iter_plan_exprs(plan):
            for node in ir.collect(
                    root, lambda n: isinstance(n, ir.Literal)
                    and isinstance(n.value, SqlParam)):
                node.value = coerced[node.value.name]
                bound += 1
        # a marker may appear in several plan operators (e.g. a WHERE
        # predicate duplicated into an aggregate prologue); every
        # occurrence must have been reached
        if self.params_used and bound == 0:
            raise StatementError(
                f"statement {self.statement_id}: internal error — no "
                f"parameter markers found in the plan template copy")
        with self._lock:
            self.executions += 1
        return plan


# ---------------------------------------------------------------------------
# Batched dispatch (serve.batch.*): same template, many bindings, one
# vectorized execution
# ---------------------------------------------------------------------------

# the only plan nodes a coalesced execution may contain: row-wise
# shapes where "filter by OR of the per-binding predicates, then split
# rows per binding host-side" is exactly equivalent to running each
# binding alone.  An aggregate/limit/sort/join anywhere would mix rows
# across bindings, so those templates always execute singly.
_BATCHABLE_NODES = (lp.Project, lp.Filter, lp.FileScan,
                    lp.InMemoryScan, lp.CachedRelation)


def batch_eligible(stmt: PreparedStatement) -> bool:
    """True when ``stmt``'s template may join a coalesced execution:
    a projection directly over one parameterized filter, row-wise
    nodes only, every parameter marker inside that filter's condition,
    nothing non-deterministic.  Computed once per statement."""
    cached = getattr(stmt, "_batch_eligible", None)
    if cached is None:
        try:
            cached = _compute_batch_eligible(stmt.plan_template,
                                             stmt.params_used)
        except Exception:
            cached = False
        stmt._batch_eligible = cached
    return cached


def _has_param(root: ir.Expression) -> bool:
    return bool(ir.collect(
        root, lambda n: isinstance(n, ir.Literal)
        and isinstance(n.value, SqlParam)))


def _compute_batch_eligible(template: lp.LogicalPlan,
                            params_used) -> bool:
    from spark_rapids_tpu.plan.digest import _NONDETERMINISTIC_EXPRS
    if not params_used:
        return False
    if not isinstance(template, lp.Project):
        return False
    filt = template.children[0]
    if not isinstance(filt, lp.Filter):
        return False
    for node in walk(template):
        if not isinstance(node, _BATCHABLE_NODES):
            return False
        for root in iter_node_exprs(node):
            if ir.collect(root, lambda n: type(n).__name__
                          in _NONDETERMINISTIC_EXPRS):
                return False
            if _has_param(root) and not (
                    node is filt and root is filt.condition):
                return False
    return True


def coalesce_bound_plans(bound_plans: List[lp.LogicalPlan]):
    """One vectorized plan answering every bound copy of one
    batch-eligible template: the filter becomes the OR of every
    binding's condition, and each binding contributes one BOOL marker
    column (``__batch_m<i>``) — a per-row record of WHICH bindings
    selected it, so the serve tier can split the single result per
    client host-side (a row matching several bindings appears in each
    of their splits, exactly as k separate executions would return
    it).  Returns ``(plan, marker_names)``."""
    first = bound_plans[0]
    base = first.children[0].children[0]
    conds = [p.children[0].condition for p in bound_plans]
    or_cond = conds[0]
    for c in conds[1:]:
        or_cond = ir.Or(or_cond, c)
    out_names = set(first.schema.names)
    exprs = list(first.exprs)
    markers: List[str] = []
    for i, c in enumerate(conds):
        name = f"__batch_m{i}"
        while name in out_names:
            name = "_" + name
        out_names.add(name)
        markers.append(name)
        exprs.append(ir.Alias(copy.deepcopy(c), name))
    return lp.Project(lp.Filter(base, or_cond), exprs), markers
