"""Thin in-repo serving client (the tests/CI driver for serve/server).

One ``ServeClient`` owns one TCP connection and one serve session.  A
background reader thread routes frames by request tag, so multiple
user threads can run queries over one connection concurrently (the
multiplexing the server is built for).  Results stream back in CHUNK
frames under a credit window: the client grants ``credit`` chunks up
front and replenishes one credit per chunk it consumes — a slow
consumer therefore bounds how far ahead the server can materialize
into the socket (the backpressure contract in serve/wire.py).
"""

from __future__ import annotations

import queue
import socket
import threading
from typing import Any, Dict, Iterator, List, Optional

import pyarrow as pa

from spark_rapids_tpu.serve import wire


class ServeError(RuntimeError):
    """Server-reported request failure (``code`` is the typed ERR
    discriminator: FairShareExceeded, SessionExpired, StatementError,
    or the engine exception's type name)."""

    def __init__(self, code: str, msg: str):
        super().__init__(f"[{code}] {msg}")
        self.code = code


class _ClosedError(ServeError):
    def __init__(self, msg: str = "connection closed"):
        super().__init__("ConnectionClosed", msg)


class PreparedHandle:
    """Client-side handle to one server-side prepared statement."""

    __slots__ = ("client", "statement_id", "columns", "params")

    def __init__(self, client: "ServeClient", desc: Dict[str, Any]):
        self.client = client
        self.statement_id = desc["statement_id"]
        self.columns = list(desc.get("columns") or [])
        self.params = dict(desc.get("params") or {})

    def execute(self, params: Optional[Dict[str, Any]] = None,
                timeout: Optional[float] = None) -> pa.Table:
        return self.client.execute(self.statement_id, params,
                                   timeout=timeout)

    def close(self) -> None:
        self.client._request({"op": "close_statement",
                              "statement_id": self.statement_id})


class ResultStream:
    """Iterator over one query's streamed result chunks; replenishes
    one credit per consumed chunk.  ``read_all()`` drains into one
    table; ``summary`` holds the END payload afterwards."""

    def __init__(self, client: "ServeClient", tag: int,
                 timeout: Optional[float]):
        self._client = client
        self._tag = tag
        self._timeout = timeout
        self.summary: Optional[Dict[str, Any]] = None
        self._done = False

    def __iter__(self) -> Iterator[pa.Table]:
        while not self._done:
            kind, payload = self._client._next_stream_item(
                self._tag, self._timeout)
            if kind == wire.CHUNK:
                self._client._grant(self._tag, 1)
                yield wire.decode_chunk(payload)
            elif kind == wire.END:
                self.summary = wire.decode_msg(payload)
                self._done = True
            else:                      # ERR
                self._done = True
                err = wire.decode_msg(payload)
                raise ServeError(err.get("type", "Error"),
                                 err.get("error", "query failed"))
        return

    def read_all(self) -> pa.Table:
        tables: List[pa.Table] = list(self)
        if not tables:
            raise ServeError("Protocol", "no result chunks received")
        return pa.concat_tables(tables)


class ServeClient:
    """See module docstring.  ``conf`` is the session overlay the
    server applies to every query this session submits:
    ``{"priority": int, "timeoutMs": int, "estimateBytes": int}``."""

    def __init__(self, host: str, port: int,
                 conf: Optional[Dict[str, Any]] = None,
                 connect_timeout: float = 10.0,
                 default_credit: int = 8):
        self._sock = socket.create_connection((host, port),
                                              timeout=connect_timeout)
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._tags = iter(range(1, 1 << 62))
        self._tag_lock = threading.Lock()
        self._pending: Dict[int, "queue.Queue"] = {}
        self._plock = threading.Lock()
        self._closed = False
        self._default_credit = max(1, int(default_credit))
        self._reader = threading.Thread(target=self._read_loop,
                                        name="serve-client-reader",
                                        daemon=True)
        self._reader.start()
        try:
            resp = self._request({"op": "hello",
                                  "conf": dict(conf or {})})
        except BaseException:
            # a failed handshake must not leak the socket and a
            # reader thread blocked in recv() forever (abort's
            # shutdown() is what actually wakes the reader)
            self.abort()
            raise
        self.session_id = resp["session_id"]

    # -- plumbing ----------------------------------------------------------
    def _next_tag(self) -> int:
        with self._tag_lock:
            return next(self._tags)

    def _read_loop(self) -> None:
        try:
            while True:
                frame = wire.read_frame(self._sock)
                if frame is None:
                    break
                kind, tag, payload = frame
                with self._plock:
                    q = self._pending.get(tag)
                if q is not None:
                    q.put((kind, payload))
        except (wire.WireError, OSError):
            pass
        finally:
            self._fail_pending()

    def _fail_pending(self) -> None:
        with self._plock:
            self._closed = True
            pending = list(self._pending.values())
        err = wire.encode_msg({"type": "ConnectionClosed",
                               "error": "connection closed"})
        for q in pending:
            q.put((wire.ERR, err))

    def _register(self, tag: int) -> "queue.Queue":
        q: "queue.Queue" = queue.Queue()
        with self._plock:
            if self._closed:
                raise _ClosedError()
            self._pending[tag] = q
        return q

    def _unregister(self, tag: int) -> None:
        with self._plock:
            self._pending.pop(tag, None)

    def _send_req(self, tag: int, msg: Dict[str, Any]) -> None:
        try:
            wire.send_frame(self._sock, self._wlock, wire.REQ, tag,
                            wire.encode_msg(msg))
        except wire.WireError as e:
            self._unregister(tag)
            raise _ClosedError(str(e)) from e

    def _grant(self, tag: int, n: int) -> None:
        try:
            wire.send_frame(self._sock, self._wlock, wire.CREDIT, tag,
                            wire.encode_msg({"n": int(n)}))
        except wire.WireError:
            pass                       # stream will fail on its own

    def _request(self, msg: Dict[str, Any],
                 timeout: Optional[float] = 60.0) -> Dict[str, Any]:
        """One control round trip (RESP/ERR)."""
        tag = self._next_tag()
        q = self._register(tag)
        try:
            self._send_req(tag, msg)
            try:
                kind, payload = q.get(timeout=timeout)
            except queue.Empty:
                raise ServeError(
                    "Timeout", f"no response to {msg.get('op')!r} "
                    f"within {timeout}s") from None
            obj = wire.decode_msg(payload)
            if kind == wire.ERR:
                raise ServeError(obj.get("type", "Error"),
                                 obj.get("error", "request failed"))
            return obj
        finally:
            self._unregister(tag)

    def _next_stream_item(self, tag: int, timeout: Optional[float]):
        with self._plock:
            q = self._pending.get(tag)
        if q is None:
            raise _ClosedError("stream already finished")
        try:
            kind, payload = q.get(
                timeout=timeout if timeout is not None else 600.0)
        except queue.Empty:
            self._unregister(tag)
            raise ServeError("Timeout",
                             f"no stream frame within {timeout}s") \
                from None
        if kind in (wire.END, wire.ERR):
            self._unregister(tag)
        return kind, payload

    def _query(self, msg: Dict[str, Any], credit: Optional[int],
               timeout: Optional[float]) -> ResultStream:
        tag = self._next_tag()
        self._register(tag)
        msg = dict(msg)
        msg["credit"] = int(credit if credit is not None
                            else self._default_credit)
        try:
            self._send_req(tag, msg)
        except BaseException:
            self._unregister(tag)
            raise
        return ResultStream(self, tag, timeout)

    # -- public surface ----------------------------------------------------
    def sql(self, sql: str, timeout: Optional[float] = None
            ) -> pa.Table:
        """Run one ad-hoc statement and return the full result."""
        return self.sql_stream(sql, timeout=timeout).read_all()

    def sql_stream(self, sql: str, credit: Optional[int] = None,
                   timeout: Optional[float] = None) -> ResultStream:
        return self._query({"op": "sql", "sql": sql}, credit, timeout)

    def prepare(self, sql: str,
                params: Optional[Dict[str, str]] = None
                ) -> PreparedHandle:
        """Prepare a ``:name``-parameterized statement; ``params`` maps
        parameter name → SQL type name (int, bigint, double, string,
        date, timestamp, ...)."""
        return PreparedHandle(self, self._request(
            {"op": "prepare", "sql": sql, "params": dict(params or {})}))

    def execute(self, statement_id: str,
                params: Optional[Dict[str, Any]] = None,
                timeout: Optional[float] = None) -> pa.Table:
        return self.execute_stream(statement_id, params,
                                   timeout=timeout).read_all()

    def execute_stream(self, statement_id: str,
                       params: Optional[Dict[str, Any]] = None,
                       credit: Optional[int] = None,
                       timeout: Optional[float] = None) -> ResultStream:
        return self._query({"op": "execute",
                            "statement_id": statement_id,
                            "params": dict(params or {})},
                           credit, timeout)

    def cancel(self, stream: ResultStream) -> bool:
        return bool(self._request(
            {"op": "cancel", "request": stream._tag}).get("cancelled"))

    def ping(self) -> bool:
        return bool(self._request({"op": "ping"}).get("ok"))

    def session_info(self) -> Dict[str, Any]:
        return self._request({"op": "session_info"})

    def close(self, end_session: bool = True) -> None:
        """Graceful close (server evicts the session when
        ``end_session``); idempotent."""
        if self._closed:
            return
        try:
            self._request({"op": "close", "end_session": end_session},
                          timeout=5.0)
        except ServeError:
            pass
        self.abort()

    def abort(self) -> None:
        """Hard close: drop the socket (the disconnect-cancel path the
        tests exercise).  shutdown() before close(): close() alone
        would neither wake this client's own blocked reader nor send
        the FIN the server's reader needs to observe the disconnect."""
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *a) -> None:
        self.close()
