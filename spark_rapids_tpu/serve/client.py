"""Thin in-repo serving client (the tests/CI driver for serve/server).

One ``ServeClient`` owns one TCP connection and one serve session.  A
background reader thread routes frames by request tag, so multiple
user threads can run queries over one connection concurrently (the
multiplexing the server is built for).  Results stream back in CHUNK
frames under a credit window: the client grants ``credit`` chunks up
front and replenishes one credit per chunk it consumes — a slow
consumer therefore bounds how far ahead the server can materialize
into the socket (the backpressure contract in serve/wire.py).

Resilience (opt-in via ``reconnect=True``): every CHUNK carries a
sequence number, so the stream iterator is duplicate-free by
construction — chunks at or below the last sequence it yielded are
dropped, a sequence hole or a lost connection triggers a resume.  On
a connection loss the client reconnects with bounded exponential
backoff, re-attaches its session by resume token (hello ``resume``),
replays any prepared statements the server no longer holds (aliasing
old statement ids to their replacements), and resumes each damaged
stream from the last chunk it yielded via ``resume_stream`` — or, if
the server's retained window lost the stream, re-executes the original
request and skips the already-yielded prefix by sequence number.
Default OFF: a plain client treats a lost connection as fatal, which
is what the disconnect-cancellation paths (and their tests) rely on.
"""

from __future__ import annotations

import itertools
import queue
import socket
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

import pyarrow as pa

from spark_rapids_tpu.serve import faults as serve_faults
from spark_rapids_tpu.serve import wire


class ServeError(RuntimeError):
    """Server-reported request failure (``code`` is the typed ERR
    discriminator: FairShareExceeded, SessionExpired, Draining,
    ProtocolError, StatementError, or the engine exception's type
    name)."""

    def __init__(self, code: str, msg: str):
        super().__init__(f"[{code}] {msg}")
        self.code = code


class _ClosedError(ServeError):
    def __init__(self, msg: str = "connection closed"):
        super().__init__("ConnectionClosed", msg)


class PreparedHandle:
    """Client-side handle to one server-side prepared statement."""

    __slots__ = ("client", "statement_id", "columns", "params")

    def __init__(self, client: "ServeClient", desc: Dict[str, Any]):
        self.client = client
        self.statement_id = desc["statement_id"]
        self.columns = list(desc.get("columns") or [])
        self.params = dict(desc.get("params") or {})

    def execute(self, params: Optional[Dict[str, Any]] = None,
                timeout: Optional[float] = None) -> pa.Table:
        return self.client.execute(self.statement_id, params,
                                   timeout=timeout)

    def close(self) -> None:
        self.client._request({"op": "close_statement",
                              "statement_id": self.statement_id})


# ERR codes that mean "the stream can be resumed after reconnecting",
# as opposed to a genuine query failure that must surface to the caller
_RESUMABLE_CODES = ("Draining", "ConnectionClosed")


class ResultStream:
    """Iterator over one query's streamed result chunks; replenishes
    one credit per consumed chunk.  ``read_all()`` drains into one
    table; ``summary`` holds the END payload afterwards.

    Duplicate-freedom: chunks are yielded strictly in sequence order;
    anything at or below ``last_seq`` is dropped (a resumed or
    re-executed stream can never double-deliver), a hole above it
    triggers a resume."""

    def __init__(self, client: "ServeClient", tag: int,
                 timeout: Optional[float], msg: Dict[str, Any],
                 stream_id: str, credit: int):
        self._client = client
        self._tag = tag
        self._timeout = timeout
        self._msg = dict(msg)          # original request, for re-execute
        self._stream_id = stream_id
        self._credit = credit
        self.summary: Optional[Dict[str, Any]] = None
        self.last_seq = 0
        self.resumes = 0
        self._done = False

    def __iter__(self) -> Iterator[pa.Table]:
        while not self._done:
            try:
                kind, payload = self._client._next_stream_item(
                    self._tag, self._timeout)
            except _ClosedError:
                self._resume_or_raise(
                    ServeError("ConnectionClosed",
                               "connection lost mid-stream"))
                continue
            if kind == wire.CHUNK:
                seq, arrow = wire.split_chunk(payload)
                if seq <= self.last_seq:
                    # replayed prefix of a re-executed stream: consumed
                    # credit, already yielded — drop, never re-yield
                    self._client._grant(self._tag, 1)
                    continue
                if seq != self.last_seq + 1:
                    # sequence hole (a dropped frame): this attempt is
                    # damaged; resume strictly after the last good chunk
                    self._resume_or_raise(ServeError(
                        "StreamDamaged",
                        f"chunk sequence hole: got {seq}, "
                        f"expected {self.last_seq + 1}"))
                    continue
                self.last_seq = seq
                self._client._grant(self._tag, 1)
                yield wire.decode_chunk(arrow)
            elif kind == wire.END:
                s = wire.decode_msg(payload)
                want = int(s.get("last_seq") or 0)
                if want and self.last_seq < want:
                    # END arrived but the tail never did (dropped
                    # chunks right before END): fetch the rest
                    self._resume_or_raise(ServeError(
                        "StreamDamaged",
                        f"stream ended at seq {self.last_seq} of "
                        f"{want}"))
                    continue
                self.summary = s
                self._done = True
                self._client._finish_stream(self._stream_id)
            else:                      # ERR
                err = wire.decode_msg(payload)
                code = err.get("type", "Error")
                if code in _RESUMABLE_CODES:
                    self._resume_or_raise(ServeError(
                        code, err.get("error", "stream interrupted")))
                    continue
                if code == "SessionExpired" and \
                        self._client._reconnect_enabled:
                    # the session was evicted under us: re-attach by
                    # resume token (a fresh hello on the live
                    # connection), then resume/re-execute
                    try:
                        self._client._rehello()
                    except ServeError:
                        pass
                    self._resume_or_raise(ServeError(
                        code, err.get("error", "session expired")))
                    continue
                if code == "ResumeUnavailable" and \
                        self._client._reconnect_enabled:
                    # the retained window lost this stream (or it never
                    # started): skip straight to re-executing the
                    # original request — the seq filter above keeps the
                    # replay duplicate-free
                    self._resume_or_raise(ServeError(
                        code, err.get("error", "resume unavailable")),
                        try_resume=False)
                    continue
                self._done = True
                raise ServeError(code,
                                 err.get("error", "query failed"))
        return

    def _resume_or_raise(self, cause: ServeError,
                         try_resume: bool = True) -> None:
        """Re-attach this stream after an interruption: reconnect if
        needed, try ``resume_stream`` from ``last_seq`` (served from
        the server's retained window), and fall back to re-executing
        the original request — the sequence filter in ``__iter__``
        keeps either path duplicate-free.  Raises ``cause`` when the
        client has reconnection disabled or exhausted."""
        cli = self._client
        if not cli._reconnect_enabled:
            self._done = True
            raise cause
        if self.resumes >= 3 * cli._max_reconnects:
            # a stream that keeps getting interrupted is a systemic
            # failure, not a blip — stop chasing it
            self._done = True
            raise cause
        cli._unregister(self._tag)
        deadline_attempts = cli._max_reconnects + 1
        for attempt in range(deadline_attempts if try_resume else 0):
            try:
                cli._ensure_alive()
            except ServeError:
                self._done = True
                raise cause
            try:
                self._tag = cli._start_stream_attempt(
                    {"op": "resume_stream",
                     "stream_id": self._stream_id,
                     "after_seq": self.last_seq}, self._credit)
                self.resumes += 1
                return
            except _ClosedError:
                continue               # lost the new connection too
            except ServeError as e:
                if e.code == "SessionExpired":
                    # the re-attach hello raced an eviction: force a
                    # fresh hello on the next loop
                    try:
                        cli._rehello()
                    except ServeError:
                        pass
                    continue
                if e.code == "Draining":
                    time.sleep(min(1.0, 0.05 * (2 ** attempt)))
                    continue
                if e.code == "ResumeUnavailable":
                    break              # fall through to re-execute
                self._done = True
                raise
        # the retained window lost the stream: re-execute the original
        # request under the SAME stream id; the seq filter drops the
        # prefix the first attempt already yielded
        for attempt in range(deadline_attempts):
            try:
                cli._ensure_alive()
                self._tag = cli._start_stream_attempt(
                    dict(self._msg), self._credit)
                self.resumes += 1
                return
            except _ClosedError:
                continue
            except ServeError as e:
                if e.code == "Draining":
                    time.sleep(min(1.0, 0.05 * (2 ** attempt)))
                    continue
                self._done = True
                raise
        self._done = True
        raise cause

    def read_all(self) -> pa.Table:
        tables: List[pa.Table] = list(self)
        if not tables:
            raise ServeError("Protocol", "no result chunks received")
        return pa.concat_tables(tables)


class ServeClient:
    """See module docstring.  ``conf`` is the session overlay the
    server applies to every query this session submits:
    ``{"priority": int, "timeoutMs": int, "estimateBytes": int}``.

    ``reconnect=True`` arms the resilience machinery: bounded
    exponential backoff (``max_reconnects`` attempts, ``backoff_s``
    base doubling per attempt), session re-attach by resume token, and
    transparent stream resume."""

    def __init__(self, host: str, port: int,
                 conf: Optional[Dict[str, Any]] = None,
                 connect_timeout: float = 10.0,
                 default_credit: int = 8,
                 reconnect: bool = False,
                 max_reconnects: int = 5,
                 backoff_s: float = 0.05,
                 auth_token: Optional[str] = None,
                 tls: bool = False,
                 tls_ca_file: Optional[str] = None):
        self._host, self._port = host, port
        self._connect_timeout = connect_timeout
        self._conf = dict(conf or {})
        self._auth_token = auth_token
        self._tls = bool(tls or tls_ca_file)
        self._tls_ca_file = tls_ca_file
        self._reconnect_enabled = bool(reconnect)
        self._max_reconnects = max(1, int(max_reconnects))
        self._backoff_s = max(0.001, float(backoff_s))
        self._wlock = threading.Lock()
        self._tags = iter(range(1, 1 << 62))
        self._tag_lock = threading.Lock()
        self._pending: Dict[int, "queue.Queue"] = {}
        self._plock = threading.Lock()
        self._closed = False
        self._user_closed = False
        self._gen = 0
        self._conn_lock = threading.RLock()
        self._default_credit = max(1, int(default_credit))
        self._stream_seq = itertools.count(1)
        self._stream_nonce = f"{id(self) & 0xFFFFFF:06x}"
        # prepared-statement replay state: original text + declared
        # types by the id WE handed out, plus old-id -> live-id aliases
        # after a replay onto a re-minted session
        self._prepared: Dict[str, Dict[str, Any]] = {}
        self._stmt_alias: Dict[str, str] = {}
        self.resume_token: Optional[str] = None
        self.reconnects = 0
        self._sock = self._connect()
        self._sock.settimeout(None)
        wire.set_low_latency(self._sock)
        self._start_reader()
        try:
            resp = self._hello()
        except BaseException:
            # a failed handshake must not leak the socket and a
            # reader thread blocked in recv() forever (abort's
            # shutdown() is what actually wakes the reader)
            self.abort()
            raise
        self.session_id = resp["session_id"]

    # -- connection plumbing ------------------------------------------------
    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self._host, self._port),
                                        timeout=self._connect_timeout)
        if self._tls:
            import ssl
            if self._tls_ca_file:
                ctx = ssl.create_default_context(
                    cafile=self._tls_ca_file)
                ctx.check_hostname = False   # fleets address by IP
            else:
                # no CA pinned: encrypt without verifying (test
                # convenience against self-signed listeners)
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            try:
                sock = ctx.wrap_socket(sock,
                                       server_hostname=self._host)
            except BaseException:
                try:
                    sock.close()
                except OSError:
                    pass
                raise
        return sock

    def _next_tag(self) -> int:
        with self._tag_lock:
            return next(self._tags)

    def _start_reader(self) -> None:
        self._reader = threading.Thread(
            target=self._read_loop, args=(self._gen, self._sock),
            name=f"serve-client-reader-g{self._gen}", daemon=True)
        self._reader.start()

    def _read_loop(self, gen: int, sock: socket.socket) -> None:
        try:
            while True:
                frame = wire.read_frame(sock)
                if frame is None:
                    break
                ev = serve_faults.check("client.read") \
                    if serve_faults.get_fault_plan() is not None else None
                if ev is not None:
                    act = serve_faults.ServeFaultAction
                    if ev.action is act.DROP:
                        continue       # discard the frame on the floor
                    if ev.action is act.CLOSE:
                        try:
                            sock.shutdown(socket.SHUT_RDWR)
                        except OSError:
                            pass
                        sock.close()
                        break
                    if ev.action is act.DELAY:
                        time.sleep(ev.delay_s)
                kind, tag, payload = frame
                with self._plock:
                    q = self._pending.get(tag)
                if q is not None:
                    q.put((kind, payload))
        except (wire.WireError, OSError):
            pass
        finally:
            self._fail_pending(gen)

    def _fail_pending(self, gen: Optional[int] = None) -> None:
        with self._plock:
            if gen is not None and gen != self._gen:
                return                 # a newer connection took over
            self._closed = True
            pending = list(self._pending.values())
        err = wire.encode_msg({"type": "ConnectionClosed",
                               "error": "connection closed"})
        for q in pending:
            q.put((wire.ERR, err))

    def _hello(self) -> Dict[str, Any]:
        """Handshake on the CURRENT socket; re-attaches by resume
        token when one is held and replays prepared statements the
        server no longer knows."""
        msg: Dict[str, Any] = {"op": "hello", "conf": self._conf}
        if self._auth_token:
            msg["auth_token"] = self._auth_token
        if self.resume_token:
            msg["resume"] = self.resume_token
        resp = self._request_inner(msg, timeout=30.0)
        self.session_id = resp["session_id"]
        self.resume_token = resp.get("resume_token") or self.resume_token
        have = set(resp.get("statements") or [])
        for old_id, spec in list(self._prepared.items()):
            live = self._stmt_alias.get(old_id, old_id)
            if live in have:
                continue
            desc = self._request_inner(
                {"op": "prepare", "sql": spec["sql"],
                 "params": spec["params"]}, timeout=30.0)
            self._stmt_alias[old_id] = desc["statement_id"]
        return resp

    def _rehello(self) -> Dict[str, Any]:
        with self._conn_lock:
            return self._hello()

    def _ensure_alive(self) -> None:
        """Reconnect (with bounded exponential backoff) if the current
        connection is dead; no-op on a live one."""
        if not self._closed:
            return
        with self._conn_lock:
            if not self._closed:
                return                 # another thread reconnected
            if self._user_closed:
                raise _ClosedError("client closed")
            if not self._reconnect_enabled:
                raise _ClosedError()
            self._fail_pending()       # orphan anything still pending
            last: Optional[BaseException] = None
            for attempt in range(self._max_reconnects):
                if attempt:
                    time.sleep(min(2.0,
                                   self._backoff_s * (2 ** attempt)))
                try:
                    sock = self._connect()
                except OSError as e:
                    last = e
                    continue
                sock.settimeout(None)
                wire.set_low_latency(sock)
                with self._plock:
                    self._gen += 1
                    self._closed = False
                self._sock = sock
                self._wlock = threading.Lock()
                self._start_reader()
                try:
                    self._hello()
                except ServeError as e:
                    last = e
                    try:
                        sock.close()
                    except OSError:
                        pass
                    with self._plock:
                        self._closed = True
                    continue
                self.reconnects += 1
                return
            raise _ClosedError(
                f"reconnect failed after {self._max_reconnects} "
                f"attempts: {last}")

    def _register(self, tag: int) -> "queue.Queue":
        q: "queue.Queue" = queue.Queue()
        with self._plock:
            if self._closed:
                raise _ClosedError()
            self._pending[tag] = q
        return q

    def _unregister(self, tag: int) -> None:
        with self._plock:
            self._pending.pop(tag, None)

    def _send_frame(self, kind: int, tag: int, payload: bytes) -> None:
        if serve_faults.get_fault_plan() is not None:
            serve_faults.send_frame_with_faults(
                self._sock, self._wlock, kind, tag, payload)
        else:
            wire.send_frame(self._sock, self._wlock, kind, tag, payload)

    def _send_req(self, tag: int, msg: Dict[str, Any]) -> None:
        try:
            self._send_frame(wire.REQ, tag, wire.encode_msg(msg))
        except wire.WireError as e:
            self._unregister(tag)
            self._fail_pending()
            raise _ClosedError(str(e)) from e

    def _grant(self, tag: int, n: int) -> None:
        try:
            self._send_frame(wire.CREDIT, tag,
                             wire.encode_msg({"n": int(n)}))
        except wire.WireError:
            pass                       # stream will fail on its own

    def _request_inner(self, msg: Dict[str, Any],
                       timeout: Optional[float]) -> Dict[str, Any]:
        """One control round trip on the current connection — no
        reconnect (the reconnect path itself calls this)."""
        tag = self._next_tag()
        q = self._register(tag)
        try:
            self._send_req(tag, msg)
            try:
                kind, payload = q.get(timeout=timeout)
            except queue.Empty:
                raise ServeError(
                    "Timeout", f"no response to {msg.get('op')!r} "
                    f"within {timeout}s") from None
            obj = wire.decode_msg(payload)
            if kind == wire.ERR:
                raise ServeError(obj.get("type", "Error"),
                                 obj.get("error", "request failed"))
            return obj
        finally:
            self._unregister(tag)

    def _request(self, msg: Dict[str, Any],
                 timeout: Optional[float] = 60.0) -> Dict[str, Any]:
        """One control round trip (RESP/ERR), reconnecting first if
        the connection is down and reconnection is armed."""
        self._ensure_alive()
        return self._request_inner(msg, timeout)

    def _finish_stream(self, stream_id: str) -> None:
        """Fire-and-forget ack that a stream was fully consumed — the
        server drops its retained replay window for it.  Best-effort:
        a failed ack only costs the server retention until LRU."""
        try:
            self._request_inner({"op": "finish_stream",
                                 "stream_id": stream_id}, timeout=5.0)
        except (ServeError, OSError):
            pass

    def _next_stream_item(self, tag: int, timeout: Optional[float]):
        with self._plock:
            q = self._pending.get(tag)
        if q is None:
            raise _ClosedError("stream already finished")
        try:
            kind, payload = q.get(
                timeout=timeout if timeout is not None else 600.0)
        except queue.Empty:
            self._unregister(tag)
            raise ServeError("Timeout",
                             f"no stream frame within {timeout}s") \
                from None
        if kind in (wire.END, wire.ERR):
            self._unregister(tag)
        return kind, payload

    def _translate(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Map a prepared-statement id through the replay alias table
        (identity for ids the server still holds)."""
        if msg.get("op") == "execute":
            sid = str(msg.get("statement_id", ""))
            live = self._stmt_alias.get(sid)
            if live is not None:
                msg = dict(msg)
                msg["statement_id"] = live
        return msg

    def _start_stream_attempt(self, msg: Dict[str, Any],
                              credit: int) -> int:
        """Register a fresh tag and send one query-shaped request
        (initial execution, resume, or re-execution)."""
        tag = self._next_tag()
        self._register(tag)
        m = self._translate(dict(msg))
        m["credit"] = int(credit)
        try:
            self._send_req(tag, m)
        except BaseException:
            self._unregister(tag)
            raise
        return tag

    def _query(self, msg: Dict[str, Any], credit: Optional[int],
               timeout: Optional[float]) -> ResultStream:
        self._ensure_alive()
        credit = int(credit if credit is not None
                     else self._default_credit)
        stream_id = f"{self._stream_nonce}-{next(self._stream_seq)}"
        msg = dict(msg)
        msg["stream_id"] = stream_id
        tag = self._start_stream_attempt(msg, credit)
        return ResultStream(self, tag, timeout, msg, stream_id, credit)

    # -- public surface ----------------------------------------------------
    def sql(self, sql: str, timeout: Optional[float] = None
            ) -> pa.Table:
        """Run one ad-hoc statement and return the full result."""
        return self.sql_stream(sql, timeout=timeout).read_all()

    def sql_stream(self, sql: str, credit: Optional[int] = None,
                   timeout: Optional[float] = None) -> ResultStream:
        return self._query({"op": "sql", "sql": sql}, credit, timeout)

    def prepare(self, sql: str,
                params: Optional[Dict[str, str]] = None
                ) -> PreparedHandle:
        """Prepare a ``:name``-parameterized statement; ``params`` maps
        parameter name → SQL type name (int, bigint, double, string,
        date, timestamp, ...)."""
        desc = self._request(
            {"op": "prepare", "sql": sql, "params": dict(params or {})})
        # keep the text + declarations so a reconnect onto a re-minted
        # session can replay the prepare and alias the id
        self._prepared[desc["statement_id"]] = {
            "sql": sql, "params": dict(params or {})}
        return PreparedHandle(self, desc)

    def execute(self, statement_id: str,
                params: Optional[Dict[str, Any]] = None,
                timeout: Optional[float] = None) -> pa.Table:
        return self.execute_stream(statement_id, params,
                                   timeout=timeout).read_all()

    def execute_stream(self, statement_id: str,
                       params: Optional[Dict[str, Any]] = None,
                       credit: Optional[int] = None,
                       timeout: Optional[float] = None) -> ResultStream:
        return self._query({"op": "execute",
                            "statement_id": statement_id,
                            "params": dict(params or {})},
                           credit, timeout)

    def cancel(self, stream: ResultStream) -> bool:
        return bool(self._request(
            {"op": "cancel", "request": stream._tag}).get("cancelled"))

    def ping(self) -> bool:
        return bool(self._request({"op": "ping"}).get("ok"))

    def session_info(self) -> Dict[str, Any]:
        return self._request({"op": "session_info"})

    def close(self, end_session: bool = True) -> None:
        """Graceful close (server evicts the session when
        ``end_session``); idempotent."""
        self._user_closed = True
        if self._closed:
            return
        try:
            self._request_inner({"op": "close",
                                 "end_session": end_session},
                                timeout=5.0)
        except ServeError:
            pass
        self.abort()

    def abort(self) -> None:
        """Hard close: drop the socket (the disconnect-cancel path the
        tests exercise).  shutdown() before close(): close() alone
        would neither wake this client's own blocked reader nor send
        the FIN the server's reader needs to observe the disconnect."""
        self._user_closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *a) -> None:
        self.close()
