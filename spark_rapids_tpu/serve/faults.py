"""Deterministic fault injection for the serving plane.

PR 1's seeded fault-harness idiom (shuffle/faults.py) ported to the
front door: a config-driven :class:`ServeFaultPlan`
(``spark.rapids.tpu.serve.test.faultPlan``) that serving code consults
at named injection points, so chaos runs against the wire protocol are
reproducible bit-for-bit.  Every fault the plan can provoke must
surface as a *typed, recoverable, observable* event — never a dead
reader thread, a leaked streamer, or a stranded client.

Injection points (consulted via :func:`check`):

==================  ======================================================
point               consulted
==================  ======================================================
``accept``          once per accepted server connection (CLOSE drops it
                    immediately, DELAY sleeps before serving)
``frame.header``    once per frame a :class:`ServeClient` sends — the
                    header leg (CORRUPT garbles header bytes, OVERSIZE
                    rewrites the u32 length past serve.wire.maxFrameBytes,
                    UNKNOWN rewrites the kind byte, TRUNCATE sends a
                    partial header then closes, SLOW drips the header
                    byte-by-byte — the slowloris client)
``frame.body``      once per nonempty frame body a client sends (CORRUPT
                    flips a payload bit, TRUNCATE sends a partial body
                    then closes, SLOW drips it byte-by-byte)
``stream.chunk``    once per CHUNK frame a server streamer sends (DROP
                    skips the send — the client sees a sequence hole and
                    resumes, CLOSE kills the connection mid-stream,
                    DELAY sleeps before sending)
``client.read``     once per frame the client reader receives (DROP
                    discards it, CLOSE drops the client's socket, DELAY
                    sleeps before delivery)
``session.lookup``  once per server-side session lookup (FAIL makes the
                    lookup miss — the session vanished, as after a
                    replica swap — forcing the client down the
                    re-hello/resume path)
==================  ======================================================

Plan grammar is shuffle/faults.py's, verbatim::

    spec      := directive (";" directive)*
    directive := "seed=" INT
               | point ":" action [ "@" N ] ( ":" field )*
    field     := "x" M  max fires | "p" P  probability | "d" MS  delay
               | "i" IDX  target index

Example — drop the 3rd streamed chunk, close the 2nd accepted
connection, and corrupt the first request body, identically every
run::

    seed=7;stream.chunk:drop@3;accept:close@2;frame.body:corrupt@1
"""

from __future__ import annotations

import enum
import random
import re
import socket
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from spark_rapids_tpu.obs import registry as _obsreg


class ServeFaultAction(enum.Enum):
    DROP = "drop"
    DELAY = "delay"
    CLOSE = "close"
    CORRUPT = "corrupt"
    TRUNCATE = "truncate"
    OVERSIZE = "oversize"
    UNKNOWN = "unknown"
    SLOW = "slow"
    FAIL = "fail"


@dataclass
class ServeFaultRule:
    point: str
    action: ServeFaultAction
    at: Optional[int] = None      # first consultation (1-based) to arm at
    prob: float = 0.0             # alternative: seeded per-consult chance
    delay_ms: float = 0.0
    max_fires: int = 1
    arg: Optional[int] = None
    fires: int = 0


@dataclass(frozen=True)
class ServeFaultEvent:
    """One fault decision returned by :func:`check`."""
    point: str
    action: ServeFaultAction
    delay_s: float = 0.0
    arg: Optional[int] = None


class ServeFaultPlan:
    """Seeded, deterministic fault schedule for the serving plane —
    the FaultPlan contract from shuffle/faults.py: ``check(point)`` is
    cheap and thread-safe, occurrence rules (``@N``) depend only on
    consultation order at that point, probability rules draw from one
    seeded RNG under the plan lock."""

    def __init__(self, rules: List[ServeFaultRule], seed: int = 0):
        self.rules = list(rules)
        self.seed = seed
        self._rng = random.Random(seed)
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.spec: Optional[str] = None

    def check(self, point: str) -> Optional[ServeFaultEvent]:
        with self._lock:
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
            for r in self.rules:
                if r.point != point or r.fires >= r.max_fires:
                    continue
                if r.prob > 0.0:
                    if self._rng.random() >= r.prob:
                        continue
                elif r.at is not None and n < r.at:
                    continue
                r.fires += 1
                _obsreg.get_registry().inc("serve.faults.injected")
                _obsreg.get_registry().inc(f"serve.faults.injected.{point}")
                return ServeFaultEvent(point, r.action,
                                       r.delay_ms / 1000.0, r.arg)
        return None

    def consultations(self, point: str) -> int:
        with self._lock:
            return self._counts.get(point, 0)

    @property
    def total_fires(self) -> int:
        with self._lock:
            return sum(r.fires for r in self.rules)

    @staticmethod
    def corrupt(payload: bytes) -> bytes:
        """Deterministically flip one bit in the middle of the payload
        (the shuffle harness's corruption)."""
        if not payload:
            return payload
        out = bytearray(payload)
        out[len(out) // 2] ^= 0x40
        return bytes(out)

    _DIRECTIVE = re.compile(r"^(?P<point>[\w.]+):(?P<action>[a-z]+)"
                            r"(?:@(?P<at>\d+))?$")

    @classmethod
    def parse(cls, spec: str) -> Optional["ServeFaultPlan"]:
        """Parse the config-string grammar; None for an empty spec,
        ValueError on a malformed one."""
        spec = (spec or "").strip()
        if not spec:
            return None
        seed = 0
        rules: List[ServeFaultRule] = []
        for directive in spec.split(";"):
            directive = directive.strip()
            if not directive:
                continue
            if directive.startswith("seed="):
                seed = int(directive[len("seed="):])
                continue
            parts = directive.split(":")
            head = ":".join(parts[:2])
            m = cls._DIRECTIVE.match(head)
            if m is None:
                raise ValueError(f"bad fault directive {directive!r}")
            rule = ServeFaultRule(
                point=m.group("point"),
                action=ServeFaultAction(m.group("action")),
                at=int(m.group("at")) if m.group("at") else None)
            for f in parts[2:]:
                f = f.strip()
                if f.startswith("x"):
                    rule.max_fires = int(f[1:])
                elif f.startswith("p"):
                    rule.prob = float(f[1:])
                elif f.startswith("d"):
                    rule.delay_ms = float(f[1:])
                elif f.startswith("i"):
                    rule.arg = int(f[1:])
                else:
                    raise ValueError(f"bad fault field {f!r} in "
                                     f"{directive!r}")
            rules.append(rule)
        plan = cls(rules, seed)
        plan.spec = spec
        return plan


# ---------------------------------------------------------------------------
# Process-wide plan (the shuffle/faults singleton idiom)
# ---------------------------------------------------------------------------

_plan: Optional[ServeFaultPlan] = None
_lock = threading.Lock()


def get_fault_plan() -> Optional[ServeFaultPlan]:
    return _plan


def set_fault_plan(plan: Optional[ServeFaultPlan]
                   ) -> Optional[ServeFaultPlan]:
    """Install (or clear, with None) the process-wide serving plan."""
    global _plan
    with _lock:
        _plan = plan
    return plan


def install_plan_from_conf(conf, fresh: bool = True
                           ) -> Optional[ServeFaultPlan]:
    """Parse ``spark.rapids.tpu.serve.test.faultPlan`` and install it.

    The shuffle install contract: an empty spec leaves a
    directly-installed plan alone but CLEARS a previously
    conf-installed one; ``fresh=True`` (server construction) re-arms a
    same-spec plan so a restarted server gets fresh consultation
    counters instead of an exhausted schedule."""
    from spark_rapids_tpu import config as cfg
    spec = str(conf.get(cfg.SERVE_FAULT_PLAN) or "").strip()
    cur = get_fault_plan()
    if not spec:
        if cur is not None and cur.spec is not None:
            set_fault_plan(None)
        return None
    if not fresh and cur is not None and cur.spec == spec:
        return cur
    return set_fault_plan(ServeFaultPlan.parse(spec))


def check(point: str) -> Optional[ServeFaultEvent]:
    """Consult the installed plan at one injection point (None when no
    plan is installed — the production fast path is one global read)."""
    plan = _plan
    if plan is None:
        return None
    return plan.check(point)


# ---------------------------------------------------------------------------
# Client-side frame mangling (frame.header / frame.body)
# ---------------------------------------------------------------------------

def send_frame_with_faults(sock: socket.socket, lock: threading.Lock,
                           kind: int, tag: int,
                           payload: bytes = b"") -> None:
    """The fault-injecting twin of ``wire.send_frame`` — the path
    :class:`ServeClient` uses while a plan is installed, so chaos runs
    can hand the server exactly the malformed bytes the hardening must
    survive.  Consults ``frame.header`` then ``frame.body`` and
    applies the fired mutation to the raw frame bytes; with no armed
    rule it degenerates to a plain framed send."""
    from spark_rapids_tpu.serve import wire
    hdr = bytearray(wire.HDR.pack(kind, tag, len(payload)))
    body = bytes(payload)
    close_after, slow_s = False, 0.0
    ev = check("frame.header")
    if ev is not None:
        if ev.action is ServeFaultAction.CORRUPT:
            hdr[0] ^= 0x5A          # garbled kind byte
            hdr[-1] ^= 0x81         # and a garbled length byte
        elif ev.action is ServeFaultAction.OVERSIZE:
            hdr = bytearray(wire.HDR.pack(kind, tag, 0xFFFF_FFF0))
            body = b""              # never send a body for the lie
            close_after = True      # the server tears the conn down
        elif ev.action is ServeFaultAction.UNKNOWN:
            hdr[0] = 0x7F           # unregistered frame kind
        elif ev.action is ServeFaultAction.TRUNCATE:
            hdr = hdr[:wire.HDR.size // 2]
            body = b""
            close_after = True
        elif ev.action is ServeFaultAction.SLOW:
            slow_s = max(ev.delay_s, 0.001)
        elif ev.action is ServeFaultAction.DELAY:
            time.sleep(ev.delay_s)
        elif ev.action is ServeFaultAction.CLOSE:
            hdr, body, close_after = bytearray(), b"", True
    if body:
        ev = check("frame.body")
        if ev is not None:
            if ev.action is ServeFaultAction.CORRUPT:
                body = ServeFaultPlan.corrupt(body)
            elif ev.action is ServeFaultAction.TRUNCATE:
                body = body[: max(1, len(body) // 2)]
                close_after = True
            elif ev.action is ServeFaultAction.SLOW:
                slow_s = max(slow_s, ev.delay_s, 0.001)
            elif ev.action is ServeFaultAction.DELAY:
                time.sleep(ev.delay_s)
    data = bytes(hdr) + body
    try:
        with lock:
            if slow_s > 0.0:
                for i in range(len(data)):      # the slowloris drip
                    sock.sendall(data[i:i + 1])
                    time.sleep(slow_s)
            elif data:
                sock.sendall(data)
        if close_after:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            sock.close()
            raise wire.WireError("connection closed by fault plan")
    except wire.WireError:
        raise
    except OSError as e:
        raise wire.WireError(f"send failed: {e}") from e
