"""Serving wire protocol: length-prefixed frames + Arrow result chunks.

Same framing idiom as the shuffle data plane (shuffle/tcp.py — and the
pyworker control channel before it): little-endian fixed header, then
the payload.

    frame := u8 kind, u64 tag, u32 len, len bytes

    REQ    := kind 1, tag = request id, payload = JSON request
    RESP   := kind 2, tag = request id, payload = JSON response
    CHUNK  := kind 3, tag = request id, payload = Arrow IPC stream
              carrying one result batch (self-contained: schema +
              batch, so any chunk decodes alone)
    ERR    := kind 4, tag = request id, payload = JSON
              {"error": str, "type": str}
    END    := kind 5, tag = request id, payload = JSON result summary
              {"rows", "chunks", "cache_hit", "query_id"}
    CREDIT := kind 6, tag = request id, payload = JSON {"n": k} —
              client -> server flow-control grant: the server may send
              k more CHUNK frames for this request (backpressure: the
              server never gets more than the client's outstanding
              credit ahead of what the client consumed)

Every request carries ``{"op": ...}``; query-shaped ops (``sql``,
``execute``) are answered with a CHUNK* END stream (or one ERR),
control ops with one RESP (or ERR).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Dict, Iterator, Optional, Tuple

import pyarrow as pa

HDR = struct.Struct("<BQI")

REQ, RESP, CHUNK, ERR, END, CREDIT = 1, 2, 3, 4, 5, 6

PROTOCOL_VERSION = 1

# a frame larger than this is a protocol violation (a desynced stream
# read as a length prefix), not a legitimate payload
MAX_FRAME_BYTES = 1 << 31


class WireError(OSError):
    """Framing/transport fault on the serving connection."""


def send_frame(sock: socket.socket, lock: threading.Lock, kind: int,
               tag: int, payload: bytes = b"") -> None:
    try:
        with lock:
            sock.sendall(HDR.pack(kind, tag, len(payload)))
            if payload:
                sock.sendall(payload)
    except OSError as e:
        raise WireError(f"send failed: {e}") from e


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError as e:
            raise WireError(f"read failed: {e}") from e
        if not chunk:
            if buf:
                raise WireError(
                    f"connection closed mid-frame ({len(buf)}/{n} bytes)")
            return None
        buf += chunk
    return bytes(buf)


def read_frame(sock: socket.socket) -> Optional[Tuple[int, int, bytes]]:
    """One frame, or None on a clean EOF at a frame boundary."""
    hdr = _recv_exact(sock, HDR.size)
    if hdr is None:
        return None
    kind, tag, ln = HDR.unpack(hdr)
    if ln > MAX_FRAME_BYTES:
        raise WireError(f"frame length {ln} exceeds protocol maximum")
    payload = _recv_exact(sock, ln) if ln else b""
    if ln and payload is None:
        return None
    return kind, tag, payload


# ---------------------------------------------------------------------------
# JSON control payloads
# ---------------------------------------------------------------------------

def encode_msg(obj: Dict[str, Any]) -> bytes:
    return json.dumps(obj, default=str).encode("utf-8")


def decode_msg(payload: bytes) -> Dict[str, Any]:
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"malformed control payload: {e}") from e
    if not isinstance(obj, dict):
        raise WireError("control payload must be a JSON object")
    return obj


# ---------------------------------------------------------------------------
# Arrow result chunks
# ---------------------------------------------------------------------------

def table_chunks(table: pa.Table, chunk_rows: int) -> Iterator[bytes]:
    """Lazily slice a result table into self-contained Arrow IPC
    stream payloads of at most ``chunk_rows`` rows each.  A generator,
    not a list: each payload serializes only after the consumer asked
    for it, so the credit-backpressure loop in serve/server.py bounds
    serialized bytes in flight (a slow client must not cost the server
    a second full copy of a large result).  A zero-row result still
    produces one chunk (schema only) so the client can always assemble
    a typed empty table."""
    chunk_rows = max(1, int(chunk_rows))
    for off in range(0, max(1, table.num_rows), chunk_rows):
        piece = table.slice(off, chunk_rows)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as w:
            for b in piece.combine_chunks().to_batches():
                w.write_batch(b)
        yield sink.getvalue().to_pybytes()


def decode_chunk(payload: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.py_buffer(payload)) as r:
        return r.read_all()
