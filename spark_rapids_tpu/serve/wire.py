"""Serving wire protocol: length-prefixed frames + Arrow result chunks.

Same framing idiom as the shuffle data plane (shuffle/tcp.py — and the
pyworker control channel before it): little-endian fixed header, then
the payload.

    frame := u8 kind, u64 tag, u32 len, len bytes

    REQ    := kind 1, tag = request id, payload = JSON request
    RESP   := kind 2, tag = request id, payload = JSON response
    CHUNK  := kind 3, tag = request id, payload = u64 sequence number
              (1-based, little-endian) + Arrow IPC stream carrying one
              result batch (self-contained: schema + batch, so any
              chunk decodes alone).  The sequence number is how a
              reconnecting client resumes a stream duplicate-free: it
              acks the last sequence it holds and the server replays
              strictly after it.
    ERR    := kind 4, tag = request id, payload = JSON
              {"error": str, "type": str, "reason": str?} — ``reason``
              is the wire-level reason code for protocol faults (see
              ServeWireError)
    END    := kind 5, tag = request id, payload = JSON result summary
              {"rows", "chunks", "cache_hit", "query_id", "last_seq"}
    CREDIT := kind 6, tag = request id, payload = JSON {"n": k} —
              client -> server flow-control grant: the server may send
              k more CHUNK frames for this request (backpressure: the
              server never gets more than the client's outstanding
              credit ahead of what the client consumed)

Every request carries ``{"op": ...}``; query-shaped ops (``sql``,
``execute``) are answered with a CHUNK* END stream (or one ERR),
control ops with one RESP (or ERR).

Hardening contract (this module is the only place serving code touches
raw sockets):

* the u32 length is validated against the caller's bound BEFORE any
  allocation — a hostile length prefix costs the server a 13-byte
  header read, never a multi-GB bytearray;
* a short read mid-frame raises a typed :class:`ServeWireError`
  (reason ``truncated``) instead of blocking a reader thread forever;
* on a socket armed with a tick timeout, :func:`read_frame` returns
  the :data:`IDLE` sentinel when no frame byte arrived (the caller's
  chance to notice drain/shutdown), and enforces ``frame_timeout_s``
  of whole-frame progress once the first byte lands (the slowloris
  defense, reason ``timeout``);
* :func:`send_frame` with ``stall_s`` bounds how long a write may sit
  with zero progress (a stalled or vanished reader, reason
  ``writeStall``) — progress resets the deadline, so a slow-but-live
  client is never punished.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Any, Dict, Iterator, Optional, Tuple

import pyarrow as pa

HDR = struct.Struct("<BQI")
SEQ = struct.Struct("<Q")

REQ, RESP, CHUNK, ERR, END, CREDIT = 1, 2, 3, 4, 5, 6
KINDS = frozenset((REQ, RESP, CHUNK, ERR, END, CREDIT))

# version 2: CHUNK payloads carry a u64 sequence prefix, sessions carry
# resume tokens, END carries last_seq
PROTOCOL_VERSION = 2

# absolute protocol ceiling (a u32 read off a desynced stream); the
# operative per-deployment bound is spark.rapids.tpu.serve.wire.
# maxFrameBytes, which callers pass as ``max_frame_bytes``
MAX_FRAME_BYTES = 1 << 31
DEFAULT_MAX_FRAME_BYTES = 256 << 20


class WireError(OSError):
    """Framing/transport fault on the serving connection."""


class ServeWireError(WireError):
    """A typed wire-protocol violation with a reason code.

    Reason codes (the ERR ``reason`` field and the
    ``serve.wire.malformedFrames.<reason>`` counter suffix):

    ==============  =====================================================
    ``oversized``   u32 length exceeds the configured frame bound
    ``truncated``   connection dropped mid-frame (short read)
    ``timeout``     frame started but stalled past the read deadline
                    (slowloris)
    ``unknownKind`` frame kind outside the protocol's registry
    ``badPayload``  undecodable control payload / malformed chunk body
    ``writeStall``  peer stopped draining our writes past the stall
                    deadline
    ==============  =====================================================
    """

    def __init__(self, msg: str, reason: str = "badPayload"):
        super().__init__(msg)
        self.reason = reason


class _Idle:
    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - repr only
        return "<wire.IDLE>"


#: returned by :func:`read_frame` on a tick-timeout socket when no
#: frame byte arrived this tick — not an error, just "nothing yet"
IDLE = _Idle()

#: frames at or under this size ride in the same send as their header
_COALESCE_BYTES = 64 * 1024


def set_low_latency(sock: socket.socket) -> None:
    """Disable Nagle on a serving-plane socket.  Control frames and
    CREDIT grants are far smaller than one MSS; letting the kernel
    batch them behind the peer's delayed ACK adds ~40ms to every
    round trip.  Best-effort: non-TCP sockets (tests use socketpairs)
    simply ignore the option."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass


def send_frame(sock: socket.socket, lock: threading.Lock, kind: int,
               tag: int, payload: bytes = b"",
               stall_s: Optional[float] = None) -> None:
    """Send one frame.  With ``stall_s`` (server streamers, whose
    sockets carry a tick timeout) the write is a progress-monitored
    loop: each tick that moves zero bytes counts against the stall
    deadline, any progress resets it, and a stall past the deadline
    raises ``ServeWireError(reason="writeStall")`` — the typed verdict
    on a client that stopped reading.  Without ``stall_s`` (client
    side, blocking sockets) it is a plain locked sendall.

    Small frames are coalesced into one send: a separate 13-byte
    header segment followed by a sub-MSS payload segment trips Nagle
    against the peer's delayed ACK (~40ms per control round trip).
    Large payloads are sent separately to avoid copying them."""
    hdr = HDR.pack(kind, tag, len(payload))
    if payload and len(payload) <= _COALESCE_BYTES:
        hdr += payload
        payload = b""
    try:
        with lock:
            if stall_s is None:
                sock.sendall(hdr)
                if payload:
                    sock.sendall(payload)
                return
            _send_all(sock, hdr, stall_s)
            if payload:
                _send_all(sock, payload, stall_s)
    except WireError:
        raise
    except OSError as e:
        raise WireError(f"send failed: {e}") from e


def _send_all(sock: socket.socket, data: bytes, stall_s: float) -> None:
    view = memoryview(data)
    deadline = time.monotonic() + stall_s
    while view:
        try:
            n = sock.send(view)
        except socket.timeout:
            if time.monotonic() >= deadline:
                raise ServeWireError(
                    f"write stalled > {stall_s:.0f}s "
                    f"({len(view)} bytes undrained)",
                    reason="writeStall") from None
            continue
        if n:
            view = view[n:]
            deadline = time.monotonic() + stall_s


def read_frame(sock: socket.socket,
               max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
               frame_timeout_s: Optional[float] = None
               ):
    """Read one frame.

    Returns ``(kind, tag, payload)``; ``None`` on a clean EOF at a
    frame boundary; :data:`IDLE` when the socket has a tick timeout
    and no frame byte arrived this tick (only possible on sockets
    armed via ``settimeout``).

    Raises :class:`ServeWireError`:

    * ``oversized`` — the u32 length exceeds ``max_frame_bytes``;
      validated before the body buffer exists, so the hostile length
      never allocates;
    * ``truncated`` — the peer vanished mid-frame;
    * ``timeout`` — the frame started but made no complete progress
      within ``frame_timeout_s`` (slowloris: deadline arms at the
      FIRST byte of the frame, so an idle keep-alive connection is
      never penalized);
    * ``unknownKind`` — the kind byte is outside :data:`KINDS` (the
      header is well-formed, so the caller may consume the declared
      body and answer with a typed ERR instead of killing the
      connection).
    """
    deadline: Optional[float] = None
    buf = bytearray()
    while len(buf) < HDR.size:
        if deadline is not None and time.monotonic() >= deadline:
            # checked on entry, not just on idle ticks: a slowloris
            # peer dripping one byte per tick never times a recv out
            raise ServeWireError(
                f"frame header stalled ({len(buf)}/{HDR.size} bytes "
                f"after {frame_timeout_s:.0f}s)", reason="timeout")
        try:
            chunk = sock.recv(HDR.size - len(buf))
        except socket.timeout:
            if not buf:
                return IDLE
            if deadline is not None and time.monotonic() >= deadline:
                raise ServeWireError(
                    f"frame header stalled ({len(buf)}/{HDR.size} bytes "
                    f"after {frame_timeout_s:.0f}s)",
                    reason="timeout") from None
            continue
        except OSError as e:
            if not buf:
                # a reset at a frame boundary is a disconnect, not a
                # malformed frame — only a mid-frame loss is typed
                return None
            raise ServeWireError(f"read failed: {e}",
                                 reason="truncated") from e
        if not chunk:
            if buf:
                raise ServeWireError(
                    f"connection closed mid-header "
                    f"({len(buf)}/{HDR.size} bytes)", reason="truncated")
            return None
        if not buf and frame_timeout_s is not None:
            deadline = time.monotonic() + frame_timeout_s
        buf += chunk
    kind, tag, ln = HDR.unpack(bytes(buf))
    bound = min(int(max_frame_bytes), MAX_FRAME_BYTES)
    if ln > bound:
        # reject on the header alone: no body buffer is ever sized by
        # an unvalidated length
        raise ServeWireError(
            f"frame length {ln} exceeds bound {bound}", reason="oversized")
    body = bytearray()
    while len(body) < ln:
        if deadline is not None and time.monotonic() >= deadline:
            raise ServeWireError(
                f"frame body stalled ({len(body)}/{ln} bytes after "
                f"{frame_timeout_s:.0f}s)", reason="timeout")
        try:
            chunk = sock.recv(min(ln - len(body), 1 << 20))
        except socket.timeout:
            if deadline is not None and time.monotonic() >= deadline:
                raise ServeWireError(
                    f"frame body stalled ({len(body)}/{ln} bytes after "
                    f"{frame_timeout_s:.0f}s)", reason="timeout") from None
            continue
        except OSError as e:
            raise ServeWireError(f"read failed: {e}",
                                 reason="truncated") from e
        if not chunk:
            raise ServeWireError(
                f"connection closed mid-body ({len(body)}/{ln} bytes)",
                reason="truncated")
        body += chunk
    if kind not in KINDS:
        # the header was well-formed and the declared body has been
        # consumed, so the stream is still in sync: carry the tag so
        # the caller can answer with a typed ERR and keep reading
        err = ServeWireError(f"unknown frame kind {kind}",
                             reason="unknownKind")
        err.tag = tag
        raise err
    return kind, tag, bytes(body)


# ---------------------------------------------------------------------------
# JSON control payloads
# ---------------------------------------------------------------------------

def encode_msg(obj: Dict[str, Any]) -> bytes:
    return json.dumps(obj, default=str).encode("utf-8")


def decode_msg(payload: bytes) -> Dict[str, Any]:
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ServeWireError(f"malformed control payload: {e}",
                             reason="badPayload") from e
    if not isinstance(obj, dict):
        raise ServeWireError("control payload must be a JSON object",
                             reason="badPayload")
    return obj


# ---------------------------------------------------------------------------
# Arrow result chunks
# ---------------------------------------------------------------------------

def table_chunks(table: pa.Table, chunk_rows: int) -> Iterator[bytes]:
    """Lazily slice a result table into self-contained Arrow IPC
    stream payloads of at most ``chunk_rows`` rows each.  A generator,
    not a list: each payload serializes only after the consumer asked
    for it, so the credit-backpressure loop in serve/server.py bounds
    serialized bytes in flight (a slow client must not cost the server
    a second full copy of a large result).  A zero-row result still
    produces one chunk (schema only) so the client can always assemble
    a typed empty table."""
    chunk_rows = max(1, int(chunk_rows))
    for off in range(0, max(1, table.num_rows), chunk_rows):
        piece = table.slice(off, chunk_rows)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as w:
            for b in piece.combine_chunks().to_batches():
                w.write_batch(b)
        yield sink.getvalue().to_pybytes()


def encode_chunk(seq: int, arrow_payload: bytes) -> bytes:
    """Prefix an Arrow chunk payload with its u64 sequence number
    (1-based position in the stream)."""
    return SEQ.pack(seq) + arrow_payload


def split_chunk(payload: bytes) -> Tuple[int, bytes]:
    """Split a CHUNK payload into (sequence number, Arrow bytes)."""
    if len(payload) < SEQ.size:
        raise ServeWireError(
            f"CHUNK payload too short for sequence prefix "
            f"({len(payload)} bytes)", reason="badPayload")
    return SEQ.unpack_from(payload)[0], payload[SEQ.size:]


def decode_chunk(payload: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.py_buffer(payload)) as r:
        return r.read_all()
