"""Multi-tenant SQL serving front-end (the "millions of users" layer).

A long-lived TCP server (``serve/server.py``) multiplexes many remote
client sessions onto one engine session's QueryService: length-prefixed
wire protocol (``serve/wire.py``), per-session conf overlays and
fair-share caps, prepared/parameterized statements
(``serve/statements.py``), a stamped result-set cache
(``serve/result_cache.py``), and chunked streaming result delivery
with client-credit backpressure.  ``serve/client.py`` is the thin
in-repo client the tests/CI drive it with.
"""

from spark_rapids_tpu.serve.client import ServeClient, ServeError  # noqa: F401
