"""Stamped result-set cache: hot dashboard queries cost zero dispatches.

Process-wide byte-budget LRU over *materialized query results*, keyed

    (canonical plan digest, output column names, source stamps)

where the digest comes from ``plan/digest.py`` (alias-insensitive, the
same canonicalization the kernel cache keys on), the output names keep
``SELECT x AS a`` and ``SELECT x AS b`` from serving each other's
schema, and the stamps are ``io/scan_cache.source_stamps`` — the
(path, mtime_ns, size) invalidation contract the scan-plan cache
already lives by.  A rewritten source file changes the stamp, so the
next lookup misses and the stale entry is purged; nothing needs to
watch the filesystem.

Only deterministic plans over stampable sources enter
(``PlanFingerprint.cacheable``), and only when the stamps captured
BEFORE execution still hold after it — a file rewritten mid-query must
not freeze a half-old result under the new stamp (the scan cache's
``handle_key`` pin, applied to whole results).

Counters (registry → /metrics): ``serve.resultCacheHits`` /
``Misses`` / ``evictedBytes`` / ``insertedBytes``.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import pyarrow as pa

from spark_rapids_tpu.obs import registry as _obsreg

_LOCK = threading.Lock()
_ENABLED = True
_MAX_BYTES = 256 << 20

# key -> (table, nbytes, inserted_unix); LRU order (oldest first)
_ENTRIES: "OrderedDict[Tuple, Tuple[pa.Table, int, float]]" = OrderedDict()
# (digest, names) -> last stamps inserted, so a fresh-stamp insert
# purges the stale-stamp entry immediately instead of waiting out LRU
_STAMP_OF: Dict[Tuple, Tuple] = {}
_TOTAL_BYTES = 0


def configure(enabled: bool, max_bytes: int) -> None:
    """Serve-server bootstrap hook (process-wide, last caller wins —
    the scan_cache.configure idiom)."""
    global _ENABLED, _MAX_BYTES
    with _LOCK:
        _ENABLED = bool(enabled)
        _MAX_BYTES = int(max_bytes)
        if not _ENABLED:
            _clear_locked()
        else:
            _evict_locked()


def enabled() -> bool:
    return _ENABLED


def clear() -> None:
    with _LOCK:
        _clear_locked()


def _clear_locked() -> None:
    global _TOTAL_BYTES
    _ENTRIES.clear()
    _STAMP_OF.clear()
    _TOTAL_BYTES = 0


def stats() -> Dict[str, int]:
    with _LOCK:
        return {"entries": len(_ENTRIES), "bytes": _TOTAL_BYTES}


def oldest_entry_age_s(now: Optional[float] = None) -> float:
    """Age in seconds of the oldest entry still resident (0.0 when the
    cache is empty) — the ``serve.resultCache.oldestEntryAgeSec`` gauge
    the /metrics scrape refreshes so operators can see how long the
    refresher has kept results warm."""
    now = time.time() if now is None else now
    with _LOCK:
        if not _ENTRIES:
            return 0.0
        oldest = min(ts for (_, _, ts) in _ENTRIES.values())
    return max(0.0, now - oldest)


def entries_info() -> List[Dict[str, Any]]:
    """Per-entry inspection rows (digest prefix, names, bytes, age,
    stamped source paths) for the ``/resultcache`` endpoint route; the
    route joins each row against the files' CURRENT stamps to report
    per-entry stamp drift."""
    now = time.time()
    with _LOCK:
        snap = [(key, nb, ts) for key, (_, nb, ts) in _ENTRIES.items()]
    out = []
    for (digest, names, stamps), nb, ts in snap:
        out.append({
            "digest": str(digest)[:48],
            "names": list(names),
            "nbytes": int(nb),
            "age_s": round(max(0.0, now - ts), 3),
            "stamps": [list(s) for s in stamps],
        })
    return out


def entry_key(digest: str, names, stamps) -> Tuple:
    return (digest, tuple(names), tuple(stamps))


def _nbytes(table: pa.Table) -> int:
    try:
        return int(table.nbytes) + 4096
    except Exception:
        return 1 << 20


def _evict_locked() -> None:
    global _TOTAL_BYTES
    reg = _obsreg.get_registry()
    while _TOTAL_BYTES > _MAX_BYTES and _ENTRIES:
        key, (_, nb, _ts) = _ENTRIES.popitem(last=False)
        _TOTAL_BYTES -= nb
        if _STAMP_OF.get(key[:2]) == key[2]:
            del _STAMP_OF[key[:2]]
        reg.inc("serve.resultCacheEvictedBytes", nb)


def lookup(digest: str, names, stamps,
           count_miss: bool = True) -> Optional[pa.Table]:
    """The cached result for (digest, names, stamps), or None.  Counts
    a hit/miss either way — the zero-dispatch claim in CI is asserted
    on these counters plus ``kernel.dispatches``.  ``count_miss=False``
    defers the miss count to the caller: the serve tier classifies a
    miss AFTER submission, because a miss that joins an in-flight
    single-flight execution is a dedup, not a second miss (counting it
    twice is exactly the racing-insert double-count this fixes)."""
    reg = _obsreg.get_registry()
    if not _ENABLED or stamps is None:
        if count_miss:
            reg.inc("serve.resultCacheMisses")
        return None
    key = entry_key(digest, names, stamps)
    with _LOCK:
        hit = _ENTRIES.get(key)
        if hit is not None:
            _ENTRIES.move_to_end(key)
    if hit is None:
        if count_miss:
            reg.inc("serve.resultCacheMisses")
        return None
    reg.inc("serve.resultCacheHits")
    return hit[0]


def peek(digest: str, names, stamps) -> Optional[pa.Table]:
    """Non-counting lookup: the cached result for (digest, names,
    stamps) with NO hit/miss accounting and no LRU promotion.  The
    stream-resume path uses this — a reconnecting client replaying the
    tail of a result it already earned must not inflate the hit-rate
    counters the zero-dispatch CI gate asserts on."""
    if not _ENABLED or stamps is None:
        return None
    with _LOCK:
        hit = _ENTRIES.get(entry_key(digest, names, stamps))
    return hit[0] if hit is not None else None


def lookup_latest(digest: str, names
                  ) -> Optional[Tuple[Tuple, pa.Table]]:
    """The most recently inserted (stamps, table) for (digest, names)
    regardless of whether those stamps still hold — the incremental
    maintainer's retained-state lookup: a stale-stamp partial is
    exactly what a delta refresh merges forward.  Counts neither a hit
    nor a miss (the caller already counted its primary lookup).
    Returns None when no entry for the pair is resident."""
    if not _ENABLED:
        return None
    with _LOCK:
        stamps = _STAMP_OF.get((digest, tuple(names)))
        if stamps is None:
            return None
        hit = _ENTRIES.get(entry_key(digest, names, stamps))
        if hit is None:
            return None
        _ENTRIES.move_to_end(entry_key(digest, names, stamps))
        return stamps, hit[0]


def insert(digest: str, names, stamps, table: pa.Table) -> bool:
    """Insert one materialized result; returns False when the cache is
    off, the entry alone exceeds the whole budget, or ``stamps`` is
    None (unstampable source).  A same-(digest, names) entry under
    OLDER stamps purges immediately."""
    global _TOTAL_BYTES
    if not _ENABLED or stamps is None:
        return False
    nb = _nbytes(table)
    if nb > _MAX_BYTES:
        return False
    key = entry_key(digest, names, stamps)
    reg = _obsreg.get_registry()
    with _LOCK:
        prev_stamps = _STAMP_OF.get(key[:2])
        if prev_stamps is not None and prev_stamps != key[2]:
            stale = _ENTRIES.pop(entry_key(digest, names, prev_stamps),
                                 None)
            if stale is not None:
                _TOTAL_BYTES -= stale[1]
        if key in _ENTRIES:
            _ENTRIES.move_to_end(key)
            _STAMP_OF[key[:2]] = key[2]
            return True
        _ENTRIES[key] = (table, nb, time.time())
        _STAMP_OF[key[:2]] = key[2]
        _TOTAL_BYTES += nb
        _evict_locked()
    reg.inc("serve.resultCacheInsertedBytes", nb)
    return True
