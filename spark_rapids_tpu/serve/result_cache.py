"""Stamped result-set cache: hot dashboard queries cost zero dispatches.

Process-wide byte-budget LRU over *materialized query results*, keyed

    (canonical plan digest, output column names, source stamps)

where the digest comes from ``plan/digest.py`` (alias-insensitive, the
same canonicalization the kernel cache keys on), the output names keep
``SELECT x AS a`` and ``SELECT x AS b`` from serving each other's
schema, and the stamps are ``io/scan_cache.source_stamps`` — the
(path, mtime_ns, size) invalidation contract the scan-plan cache
already lives by.  A rewritten source file changes the stamp, so the
next lookup misses and the stale entry is purged; nothing needs to
watch the filesystem.

Only deterministic plans over stampable sources enter
(``PlanFingerprint.cacheable``), and only when the stamps captured
BEFORE execution still hold after it — a file rewritten mid-query must
not freeze a half-old result under the new stamp (the scan cache's
``handle_key`` pin, applied to whole results).

With a fleet store attached (``configure_store`` — fleet.enabled),
the cache becomes two-level: a local miss consults the shared store
under a digest of the SAME (plan digest, names, stamps) key, so a
result one replica executed serves a sibling's lookup with zero
dispatches there; because the LIVE stamps are part of the store key,
stamp drift invalidates fleet-wide with no coordination (an entry
published under old stamps is simply never addressed again).  A
``latest`` pointer keyed on (digest, names) mirrors ``_STAMP_OF`` so
``lookup_latest`` — the incremental maintainer's retained-partial
lookup — also resolves through the store, which is what lets replica
B delta-refresh partials replica A captured.  No store attached (the
default): every branch below short-circuits on ``_STORE is None`` and
behavior is byte-for-byte the single-process cache.

Counters (registry → /metrics): ``serve.resultCacheHits`` /
``Misses`` / ``evictedBytes`` / ``insertedBytes`` /
``SharedHits`` (hits served from the fleet store).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import pyarrow as pa

from spark_rapids_tpu.obs import registry as _obsreg

_LOCK = threading.Lock()
_ENABLED = True
_MAX_BYTES = 256 << 20
_STORE = None                       # fleet.store.FleetStore when fleeted
_STORE_MAX_ENTRY = 64 << 20
_NS_RESULT = "result"
_NS_LATEST = "latest"

# key -> (table, nbytes, inserted_unix); LRU order (oldest first)
_ENTRIES: "OrderedDict[Tuple, Tuple[pa.Table, int, float]]" = OrderedDict()
# (digest, names) -> last stamps inserted, so a fresh-stamp insert
# purges the stale-stamp entry immediately instead of waiting out LRU
_STAMP_OF: Dict[Tuple, Tuple] = {}
_TOTAL_BYTES = 0


def configure(enabled: bool, max_bytes: int) -> None:
    """Serve-server bootstrap hook (process-wide, last caller wins —
    the scan_cache.configure idiom)."""
    global _ENABLED, _MAX_BYTES
    with _LOCK:
        _ENABLED = bool(enabled)
        _MAX_BYTES = int(max_bytes)
        if not _ENABLED:
            _clear_locked()
        else:
            _evict_locked()


def configure_store(store, max_entry_bytes: int = 64 << 20) -> None:
    """Attach (or detach, with None) the fleet's shared store.  Local
    semantics are unchanged; the store only adds a second-level lookup
    and a best-effort publish on insert."""
    global _STORE, _STORE_MAX_ENTRY
    with _LOCK:
        _STORE = store
        _STORE_MAX_ENTRY = int(max_entry_bytes)


def store_attached() -> bool:
    return _STORE is not None


def enabled() -> bool:
    return _ENABLED


def clear() -> None:
    with _LOCK:
        _clear_locked()


def _clear_locked() -> None:
    global _TOTAL_BYTES
    _ENTRIES.clear()
    _STAMP_OF.clear()
    _TOTAL_BYTES = 0


def stats() -> Dict[str, int]:
    with _LOCK:
        return {"entries": len(_ENTRIES), "bytes": _TOTAL_BYTES}


def oldest_entry_age_s(now: Optional[float] = None) -> float:
    """Age in seconds of the oldest entry still resident (0.0 when the
    cache is empty) — the ``serve.resultCache.oldestEntryAgeSec`` gauge
    the /metrics scrape refreshes so operators can see how long the
    refresher has kept results warm."""
    now = time.time() if now is None else now
    with _LOCK:
        if not _ENTRIES:
            return 0.0
        oldest = min(ts for (_, _, ts) in _ENTRIES.values())
    return max(0.0, now - oldest)


def entries_info() -> List[Dict[str, Any]]:
    """Per-entry inspection rows (digest prefix, names, bytes, age,
    stamped source paths) for the ``/resultcache`` endpoint route; the
    route joins each row against the files' CURRENT stamps to report
    per-entry stamp drift."""
    now = time.time()
    with _LOCK:
        snap = [(key, nb, ts) for key, (_, nb, ts) in _ENTRIES.items()]
    out = []
    for (digest, names, stamps), nb, ts in snap:
        out.append({
            "digest": str(digest)[:48],
            "names": list(names),
            "nbytes": int(nb),
            "age_s": round(max(0.0, now - ts), 3),
            "stamps": [list(s) for s in stamps],
        })
    return out


def entry_key(digest: str, names, stamps) -> Tuple:
    return (digest, tuple(names), tuple(stamps))


def _nbytes(table: pa.Table) -> int:
    try:
        return int(table.nbytes) + 4096
    except Exception:
        return 1 << 20


def _store_key(digest: str, names, stamps) -> str:
    """Content-addressed store key: the live stamps are part of it, so
    drifted sources change the address and the stale value is never
    read again — invalidation by construction, fleet-wide."""
    blob = json.dumps([str(digest), list(names),
                       [list(s) for s in stamps]], default=str)
    return "r" + hashlib.sha1(blob.encode("utf-8")).hexdigest()


def _latest_key(digest: str, names) -> str:
    blob = json.dumps([str(digest), list(names)], default=str)
    return "l" + hashlib.sha1(blob.encode("utf-8")).hexdigest()


def _table_to_ipc(table: pa.Table) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    return sink.getvalue().to_pybytes()


def _table_from_ipc(data: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.py_buffer(data)) as reader:
        return reader.read_all()


def _store_fetch(store, digest: str, names, stamps) -> Optional[pa.Table]:
    """Second-level lookup (no locks held — store IO can block)."""
    try:
        raw = store.get(_NS_RESULT, _store_key(digest, names, stamps))
        if raw is None:
            return None
        return _table_from_ipc(raw)
    except Exception:
        _obsreg.get_registry().inc("fleet.store.errors")
        return None


def _store_publish(store, digest: str, names, stamps,
                   table: pa.Table, nb: int) -> None:
    """Best-effort publish after a local insert (no locks held)."""
    if nb > _STORE_MAX_ENTRY:
        return
    try:
        data = _table_to_ipc(table)
        if len(data) > _STORE_MAX_ENTRY:
            return
        store.put(_NS_RESULT, _store_key(digest, names, stamps), data)
        pointer = json.dumps({"stamps": [list(s) for s in stamps]},
                             default=str)
        store.put(_NS_LATEST, _latest_key(digest, names),
                  pointer.encode("utf-8"))
    except Exception:
        _obsreg.get_registry().inc("fleet.store.errors")


def _deep_tuple(v):
    return tuple(_deep_tuple(x) for x in v) if isinstance(v, list) else v


def _stamps_from_pointer(raw: bytes) -> Optional[Tuple]:
    try:
        doc = json.loads(raw.decode("utf-8"))
        # JSON turned every nesting level into lists; stamps must come
        # back as the hashable tuples entry_key and the incremental
        # maintainer compare against
        return _deep_tuple(doc["stamps"])
    except Exception:
        return None


def _adopt(digest: str, names, stamps, table: pa.Table) -> None:
    """Install a store-fetched entry locally (no re-publish)."""
    global _TOTAL_BYTES
    nb = _nbytes(table)
    if nb > _MAX_BYTES:
        return
    key = entry_key(digest, names, stamps)
    with _LOCK:
        if key in _ENTRIES:
            _ENTRIES.move_to_end(key)
            return
        prev_stamps = _STAMP_OF.get(key[:2])
        if prev_stamps is not None and prev_stamps != key[2]:
            stale = _ENTRIES.pop(entry_key(digest, names, prev_stamps),
                                 None)
            if stale is not None:
                _TOTAL_BYTES -= stale[1]
        _ENTRIES[key] = (table, nb, time.time())
        _STAMP_OF[key[:2]] = key[2]
        _TOTAL_BYTES += nb
        _evict_locked()


def _evict_locked() -> None:
    global _TOTAL_BYTES
    reg = _obsreg.get_registry()
    while _TOTAL_BYTES > _MAX_BYTES and _ENTRIES:
        key, (_, nb, _ts) = _ENTRIES.popitem(last=False)
        _TOTAL_BYTES -= nb
        if _STAMP_OF.get(key[:2]) == key[2]:
            del _STAMP_OF[key[:2]]
        reg.inc("serve.resultCacheEvictedBytes", nb)


def lookup(digest: str, names, stamps,
           count_miss: bool = True) -> Optional[pa.Table]:
    """The cached result for (digest, names, stamps), or None.  Counts
    a hit/miss either way — the zero-dispatch claim in CI is asserted
    on these counters plus ``kernel.dispatches``.  ``count_miss=False``
    defers the miss count to the caller: the serve tier classifies a
    miss AFTER submission, because a miss that joins an in-flight
    single-flight execution is a dedup, not a second miss (counting it
    twice is exactly the racing-insert double-count this fixes)."""
    reg = _obsreg.get_registry()
    if not _ENABLED or stamps is None:
        if count_miss:
            reg.inc("serve.resultCacheMisses")
        return None
    key = entry_key(digest, names, stamps)
    with _LOCK:
        hit = _ENTRIES.get(key)
        if hit is not None:
            _ENTRIES.move_to_end(key)
        store = _STORE
    if hit is None and store is not None:
        shared = _store_fetch(store, digest, names, stamps)
        if shared is not None:
            _adopt(digest, names, stamps, shared)
            reg.inc("serve.resultCacheHits")
            reg.inc("serve.resultCacheSharedHits")
            return shared
    if hit is None:
        if count_miss:
            reg.inc("serve.resultCacheMisses")
        return None
    reg.inc("serve.resultCacheHits")
    return hit[0]


def peek(digest: str, names, stamps) -> Optional[pa.Table]:
    """Non-counting lookup: the cached result for (digest, names,
    stamps) with NO hit/miss accounting and no LRU promotion.  The
    stream-resume path uses this — a reconnecting client replaying the
    tail of a result it already earned must not inflate the hit-rate
    counters the zero-dispatch CI gate asserts on."""
    if not _ENABLED or stamps is None:
        return None
    with _LOCK:
        hit = _ENTRIES.get(entry_key(digest, names, stamps))
    return hit[0] if hit is not None else None


def lookup_latest(digest: str, names
                  ) -> Optional[Tuple[Tuple, pa.Table]]:
    """The most recently inserted (stamps, table) for (digest, names)
    regardless of whether those stamps still hold — the incremental
    maintainer's retained-state lookup: a stale-stamp partial is
    exactly what a delta refresh merges forward.  Counts neither a hit
    nor a miss (the caller already counted its primary lookup).
    Returns None when no entry for the pair is resident."""
    if not _ENABLED:
        return None
    with _LOCK:
        stamps = _STAMP_OF.get((digest, tuple(names)))
        hit = (_ENTRIES.get(entry_key(digest, names, stamps))
               if stamps is not None else None)
        if hit is not None:
            _ENTRIES.move_to_end(entry_key(digest, names, stamps))
        store = _STORE
    if hit is not None:
        return stamps, hit[0]
    if store is None:
        return None
    # the shared 'latest' pointer: what _STAMP_OF is locally — this is
    # the hop that lets a replica delta-refresh partials a SIBLING
    # captured (the maintainer keys partials digest+PARTIAL_SUFFIX)
    try:
        raw = store.get(_NS_LATEST, _latest_key(digest, names))
    except Exception:
        _obsreg.get_registry().inc("fleet.store.errors")
        return None
    if raw is None:
        return None
    pstamps = _stamps_from_pointer(raw)
    if pstamps is None:
        return None
    shared = _store_fetch(store, digest, names, pstamps)
    if shared is None:
        return None
    _adopt(digest, names, pstamps, shared)
    _obsreg.get_registry().inc("serve.resultCacheSharedHits")
    return pstamps, shared


def insert(digest: str, names, stamps, table: pa.Table) -> bool:
    """Insert one materialized result; returns False when the cache is
    off, the entry alone exceeds the whole budget, or ``stamps`` is
    None (unstampable source).  A same-(digest, names) entry under
    OLDER stamps purges immediately."""
    global _TOTAL_BYTES
    if not _ENABLED or stamps is None:
        return False
    nb = _nbytes(table)
    if nb > _MAX_BYTES:
        return False
    key = entry_key(digest, names, stamps)
    reg = _obsreg.get_registry()
    with _LOCK:
        prev_stamps = _STAMP_OF.get(key[:2])
        if prev_stamps is not None and prev_stamps != key[2]:
            stale = _ENTRIES.pop(entry_key(digest, names, prev_stamps),
                                 None)
            if stale is not None:
                _TOTAL_BYTES -= stale[1]
        if key in _ENTRIES:
            _ENTRIES.move_to_end(key)
            _STAMP_OF[key[:2]] = key[2]
            return True
        _ENTRIES[key] = (table, nb, time.time())
        _STAMP_OF[key[:2]] = key[2]
        _TOTAL_BYTES += nb
        _evict_locked()
        store = _STORE
    reg.inc("serve.resultCacheInsertedBytes", nb)
    if store is not None:
        _store_publish(store, digest, names, stamps, table, nb)
    return True
