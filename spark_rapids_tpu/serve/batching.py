"""Micro-batched prepared-statement dispatch (serve.batch.*).

When several clients execute the SAME prepared-statement template with
different bindings inside one short window, the statements coalesce
into ONE vectorized execution: the filter becomes the OR of every
binding's predicate, each binding rides along as a BOOL marker column
(``statements.coalesce_bound_plans``), and the single result splits
per client host-side.  PR 12's erased kernel ABI makes this
compile-free across binding values — the coalesced plan compiles once
per batch WIDTH, never per binding.

Eligibility is a static property of the template
(``statements.batch_eligible``): a projection directly over one
parameterized filter, row-wise nodes only.  Aggregates, limits, sorts
and joins always execute singly — an OR'd filter would mix rows
across bindings there.

Lifecycle of one execute request through the batcher::

    offer (fair-share slot taken, inflight tracked)
      -> window timer (serve.batch.windowMs) or a full batch
        -> flush: bind each item; result-cache hits stream cached;
           one leftover runs the normal single path; >= 2 coalesce
             -> one scheduler.submit, split per marker, stream each
                under its own credit window; per-item results enter
                the result cache under the pre/post stamp pin

Every path releases the item's fair-share slot through the server's
once-only ``_releaser``.  One-knob revert: ``serve.batch.enabled``
off bypasses the batcher entirely (the server never constructs it).

Counters: ``serve.batch.coalesced`` (statements that joined a
vectorized run), ``serve.batch.vectorizedExecutions`` (runs).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from spark_rapids_tpu.obs import recorder as obsrec
from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.serve import result_cache
from spark_rapids_tpu.serve import statements as stmts


class _Item:
    """One client's execute request parked in the window."""

    __slots__ = ("conn", "tag", "sess", "stmt", "params", "credit",
                 "stream_id", "infl")

    def __init__(self, conn, tag, sess, stmt, params, credit,
                 stream_id, infl):
        self.conn = conn
        self.tag = tag
        self.sess = sess
        self.stmt = stmt
        self.params = params
        self.credit = credit
        self.stream_id = stream_id
        self.infl = infl


class _Bound:
    """An item bound to an executable plan + its cache identity."""

    __slots__ = ("item", "plan", "digest", "cacheable", "cache_key",
                 "names", "stamps")

    def __init__(self, item, plan, digest, cacheable, cache_key,
                 names, stamps):
        self.item = item
        self.plan = plan
        self.digest = digest
        self.cacheable = cacheable
        self.cache_key = cache_key
        self.names = names
        self.stamps = stamps


class _Batch:
    __slots__ = ("key", "items", "timer")

    def __init__(self, key):
        self.key = key
        self.items: List[_Item] = []
        self.timer: Optional[threading.Timer] = None


class StatementBatcher:
    """One per ServeServer (constructed only when serve.batch.enabled)."""

    def __init__(self, server, window_ms: int, max_statements: int):
        self._server = server
        self._window_s = max(0.0, int(window_ms) / 1e3)
        self._max = max(1, int(max_statements))
        self._lock = threading.Lock()
        self._pending: Dict[Any, _Batch] = {}

    # -- intake --------------------------------------------------------------
    def offer(self, conn, tag, sess, stmt, msg: Dict[str, Any]) -> bool:
        """Park one execute request in the batching window.  False when
        the template is not batch-eligible — the caller runs the normal
        single-execution path.  On True the request is owned by the
        batcher: fair-share slot held, inflight tracked, a response
        (chunks or a typed ERR) guaranteed by flush."""
        if not stmts.batch_eligible(stmt):
            return False
        from spark_rapids_tpu.serve.server import _Inflight
        self._server._begin_or_raise(sess)
        infl = _Inflight(tag, None, int(msg.get("credit", 8)),
                         template=stmt.sql)
        conn.track(infl)
        item = _Item(conn, tag, sess, stmt, dict(msg.get("params") or {}),
                     int(msg.get("credit", 8)), msg.get("stream_id"),
                     infl)
        key = (stmt.sql, tuple(sorted(stmt.declared_types.items())))
        flush_now = None
        with self._lock:
            b = self._pending.get(key)
            if b is None:
                b = _Batch(key)
                self._pending[key] = b
                b.timer = threading.Timer(self._window_s,
                                          self._flush, args=(key, b))
                b.timer.daemon = True
                b.timer.start()
            b.items.append(item)
            if len(b.items) >= self._max:
                self._pending.pop(key, None)
                flush_now = b
        if flush_now is not None:
            if flush_now.timer is not None:
                flush_now.timer.cancel()
            self._spawn(flush_now.items)
        return True

    def flush_all(self) -> None:
        """Drain/shutdown hook: flush every parked batch immediately."""
        with self._lock:
            batches = list(self._pending.values())
            self._pending.clear()
        for b in batches:
            if b.timer is not None:
                b.timer.cancel()
            self._spawn(b.items)

    def _flush(self, key, b: _Batch) -> None:
        with self._lock:
            if self._pending.get(key) is b:
                del self._pending[key]
            items = list(b.items)
        if items:
            self._run_batch(items)

    def _spawn(self, items: List[_Item]) -> None:
        if not items:
            return
        t = threading.Thread(target=self._run_batch, args=(items,),
                             name="serve-batch-flush", daemon=True)
        t.start()

    # -- execution -----------------------------------------------------------
    def _run_batch(self, items: List[_Item]) -> None:
        srv = self._server
        pending: List[_Bound] = []
        for it in items:
            try:
                plan = it.stmt.bind(it.params)
            except Exception as e:
                self._fail_item(it, type(e).__name__, str(e))
                continue
            digest = cache_key = names = stamps = None
            cacheable = False
            served = False
            try:
                from spark_rapids_tpu.exec import incremental
                from spark_rapids_tpu.plan.digest import plan_fingerprint
                fp = plan_fingerprint(plan)
                digest = fp.digest
                cache_key = f"{srv._semantics_stamp}:{fp.digest}"
                names = tuple(plan.schema.names)
                if fp.cacheable and result_cache.enabled():
                    stamps = incremental.current_stamps(plan)
                cacheable = stamps is not None
                if cacheable:
                    hit = result_cache.lookup(cache_key, names, stamps,
                                              count_miss=False)
                    if hit is not None:
                        from spark_rapids_tpu.obs import \
                            accounting as acct
                        acct.charge_tenant(
                            it.sess.session_id, it.stmt.sql, digest,
                            "serve.resultCacheHits", 1)
                        srv._spawn_streamer(
                            it.conn, it.tag, srv._stream_cached,
                            (it.conn, it.sess, it.infl, hit,
                             it.stream_id, (cache_key, names, stamps)))
                        served = True
            except Exception:
                cacheable = False
            if not served:
                pending.append(_Bound(it, plan, digest, cacheable,
                                      cache_key, names, stamps))
        if not pending:
            return
        if len(pending) == 1:
            self._run_single(pending[0])
            return
        try:
            cplan, markers = stmts.coalesce_bound_plans(
                [b.plan for b in pending])
        except Exception:
            # a template that slipped past the static eligibility gate
            # (or a shape drift): run everyone singly, never fail them
            for b in pending:
                self._run_single(b)
            return
        self._run_coalesced(pending, cplan, markers)

    def _run_single(self, b: _Bound) -> None:
        """The `_start_query` submit tail for one already-bound item
        whose fair-share slot is already held.  Batch-eligible
        templates are maintainer-ineligible by construction (no root
        aggregate), so inc_ctx is always None here."""
        srv = self._server
        it = b.item
        try:
            eng = srv._engine()
            meta = {"session_id": it.sess.session_id,
                    "client_addr": it.sess.client_addr,
                    "statement_template": it.stmt.sql}
            if b.digest is not None:
                meta["plan_digest"] = b.digest
                meta["plan_cacheable"] = b.cacheable
            fut = eng.scheduler.submit(
                b.plan, priority=it.sess.priority,
                timeout_ms=it.sess.timeout_ms,
                estimate_bytes=it.sess.estimate_bytes, meta=meta)
        except BaseException as e:
            self._fail_item(it, type(e).__name__, str(e))
            return
        is_follower = getattr(fut, "dedup_of", None) is not None
        if b.cacheable:
            miss_name = ("serve.resultCacheDedupedFollowers"
                         if is_follower else "serve.resultCacheMisses")
            obsreg.get_registry().inc(miss_name)
            from spark_rapids_tpu.obs import accounting as acct
            acct.charge_tenant(it.sess.session_id, it.stmt.sql,
                               b.digest, miss_name, 1)
        it.infl.future = fut
        srv._spawn_streamer(
            it.conn, it.tag, srv._stream_result,
            (it.conn, it.sess, it.infl, b.cache_key, b.names,
             b.stamps, b.cacheable and not is_follower, b.plan, None,
             it.stream_id))

    def _run_coalesced(self, pending: List[_Bound], cplan,
                       markers: List[str]) -> None:
        from spark_rapids_tpu.obs import accounting as acct
        srv = self._server
        reg = obsreg.get_registry()
        first = pending[0].item

        def member_tenant(b: _Bound):
            return acct.tenant_of(b.item.sess.session_id,
                                  b.item.stmt.sql, b.digest)

        try:
            eng = srv._engine()
            fut = eng.scheduler.submit(
                cplan, priority=first.sess.priority,
                timeout_ms=first.sess.timeout_ms,
                estimate_bytes=first.sess.estimate_bytes,
                meta={"session_id": first.sess.session_id,
                      "client_addr": first.sess.client_addr,
                      "statement_template": first.stmt.sql,
                      "batched_statements": len(pending)})
        except BaseException as e:
            for b in pending:
                self._fail_item(b.item, type(e).__name__, str(e))
            return
        reg.inc("serve.batch.coalesced", len(pending))
        reg.inc("serve.batch.vectorizedExecutions")
        obsrec.record_event("serve.batchCoalesced", query=fut.query_id,
                            statements=len(pending))
        try:
            table = fut.result()
        except BaseException as e:
            # the held execution record still carries the bill — split
            # it equally so a failed batch can't strand or lose charges
            acct.settle_batch(fut.query_id,
                              [(member_tenant(b), 1.0) for b in pending])
            for b in pending:
                self._fail_item(b.item, type(e).__name__, str(e))
            return
        marker_set = set(markers)
        keep = [i for i, n in enumerate(table.column_names)
                if n not in marker_set]
        members = []
        for i, b in enumerate(pending):
            try:
                mask = table.column(markers[i])
                sub = table.filter(mask).select(keep)
            except Exception as e:
                members.append((member_tenant(b), 0.0))
                self._fail_item(b.item, type(e).__name__, str(e))
                continue
            members.append((member_tenant(b), float(sub.num_rows)))
            if b.cacheable:
                reg.inc("serve.resultCacheMisses")
                acct.charge_tenant(b.item.sess.session_id,
                                   b.item.stmt.sql, b.digest,
                                   "serve.resultCacheMisses", 1)
                # per-item insert under the serve pre/post-stamp pin
                try:
                    from spark_rapids_tpu.exec import incremental
                    post = incremental.current_stamps(b.plan)
                    if post is not None and post == b.stamps:
                        result_cache.insert(b.cache_key, b.names,
                                            b.stamps, sub)
                except Exception:
                    pass
            srv._spawn_streamer(b.item.conn, b.item.tag,
                                self._stream_split,
                                (b.item, sub, fut.query_id))
        # split the coalesced execution's held bill across the member
        # tenants by result-row share (zero rows everywhere degrades
        # to an equal split inside settle_batch)
        acct.settle_batch(fut.query_id, members)

    def _stream_split(self, it: _Item, table, query_id) -> None:
        srv = self._server
        from spark_rapids_tpu.serve.server import _retain_stream
        release = srv._releaser(it.conn, it.sess, it.infl)
        try:
            _retain_stream(it.sess.resume_token, it.stream_id,
                           table=table)
            srv._stream_table(it.conn, it.infl, table, cache_hit=False,
                              query_id=query_id, release=release)
        finally:
            release()

    def _fail_item(self, it: _Item, code: str, msg: str) -> None:
        release = self._server._releaser(it.conn, it.sess, it.infl)
        try:
            if it.conn.alive:
                self._server._send_err(it.conn, it.tag, code, msg)
        finally:
            release()
