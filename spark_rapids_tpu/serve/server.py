"""The serving front-end: a long-lived TCP server over QueryService.

One ``ServeServer`` per engine session when ``serve.enabled=true``
(api/session.py keeps it on ``session.serve_server``; ``serve.port=0``
binds ephemeral, discover via ``serve_server.port``).  Layering::

    ServeClient ──wire──> ServeServer ──submit(meta)──> QueryService
                             │                             (PR 5)
                             ├─ ServeSession  (conf overlay, fair share,
                             │                 prepared statements,
                             │                 idle eviction,
                             │                 resume token)
                             └─ result_cache  (digest+stamp keyed)

Per connection a reader thread owns the socket's inbound side; query
ops submit asynchronously and a per-query streamer thread delivers
CHUNK frames under the client's credit (wire.py) — the reader stays
responsive for CREDIT and cancel frames while results stream.  A dead
socket cancels every in-flight query through PR 5's CancelToken, so an
abandoned query releases its admission slot, drains its prefetcher and
frees its spill-catalog entries exactly like an explicit cancel.

Fair share: at most ``serve.session.maxInFlight`` queries per session
may be in flight; past it the request is refused with a typed
``FairShareExceeded`` error (back-pressure to THAT client) instead of
queueing — one greedy client cannot monopolize ``sched.memoryBudget``.

Hardening contract (the reference's graceful-degradation bar applied
to the front door): every byte off the wire is hostile until
validated.  Frame lengths are bounded before allocation
(``serve.wire.maxFrameBytes``), per-connection reads carry a
whole-frame progress deadline (``serve.wire.readTimeoutMs``, the
slowloris defense), streamer writes carry a zero-progress stall bound
(``serve.wire.writeStallMs``), and every malformed frame is answered
with a reason-coded ERR + ``serve.wire.malformedFrames.<reason>``
counter instead of a dead reader thread.  A malformed-frame storm
(``serve.wire.stormThreshold``) dumps one flight-recorder bundle with
reason "protocol".

Drain + resume: :meth:`ServeServer.drain` stops accepting, lets
in-flight streams finish inside ``serve.drain.deadlineMs``, cancels
stragglers with a typed ``Draining`` error, and tears down
leak-audited (streamer threads joined, admission slots released,
credit state dropped).  Sessions carry resume tokens and CHUNK frames
carry sequence numbers, so a :class:`ServeClient` that reconnects
after the drain re-attaches its session and resumes a stream from the
last chunk it holds — served duplicate-free from the process-global
retained-stream window (``serve.stream.retainBytes``) or the result
cache, both of which survive the drain/restart cycle.
"""

from __future__ import annotations

import itertools
import math
import os
import socket
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from spark_rapids_tpu.obs import recorder as obsrec
from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.serve import faults as serve_faults
from spark_rapids_tpu.serve import result_cache, wire
from spark_rapids_tpu.serve.faults import ServeFaultAction
from spark_rapids_tpu.serve.statements import (PreparedStatement,
                                               StatementError)

# a streamer blocked on client credit longer than this aborts: a
# wedged consumer must not pin its result table and fair-share slot
# forever (idle eviction only covers sessions with nothing in flight)
_STREAM_STALL_S = 300.0

# socket tick: reader recv / streamer send block at most this long per
# syscall, so deadline checks, drain flags and stop events are always
# observed promptly without dedicated watchdog threads
_TICK = 0.1


class ServeError(Exception):
    """Typed server-side request failure; ``code`` rides the ERR frame."""

    def __init__(self, code: str, msg: str):
        super().__init__(msg)
        self.code = code


# ---------------------------------------------------------------------------
# Process-global resume state: survives a drain/restart cycle inside
# the process (the single-replica analog of an external session store)
# ---------------------------------------------------------------------------

_RESUME_LOCK = threading.Lock()
# resume token -> the hello overlay, so a re-hello after the original
# session was evicted/drained can mint an equivalent session (bounded
# LRU: tokens are cheap, but unbounded would be a leak by another name)
_RESUME_SESSIONS: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
_RESUME_CAP = 4096

# (resume token, stream id) -> retained stream entry: either a pinned
# result table (byte-accounted against _RETAIN_CAP) or a zero-cost
# reference into the result cache.  This is the window a reconnecting
# client resumes from; the client's finish_stream ack releases it.
_RETAIN_LOCK = threading.Lock()
_RETAINED: "OrderedDict[Tuple[str, str], Dict[str, Any]]" = OrderedDict()
_RETAINED_BYTES = 0
_RETAIN_CAP = 128 << 20


def _register_resume(token: str, overlay: Dict[str, Any]) -> None:
    with _RESUME_LOCK:
        _RESUME_SESSIONS.pop(token, None)
        _RESUME_SESSIONS[token] = dict(overlay or {})
        while len(_RESUME_SESSIONS) > _RESUME_CAP:
            _RESUME_SESSIONS.popitem(last=False)


def _resume_overlay(token: str) -> Optional[Dict[str, Any]]:
    with _RESUME_LOCK:
        overlay = _RESUME_SESSIONS.get(token)
        if overlay is not None:
            _RESUME_SESSIONS.move_to_end(token)
        return dict(overlay) if overlay is not None else None


def _publish_retained_locked() -> None:
    reg = obsreg.get_registry()
    reg.set_gauge("serve.retainedStreams", len(_RETAINED))
    reg.set_gauge("serve.retainedStreamBytes", _RETAINED_BYTES)


def _retain_stream(token: Optional[str], stream_id: Optional[str],
                   table=None, cache_ref: Optional[Tuple] = None) -> None:
    """Retain one stream's replay source under (token, stream_id):
    either the table itself (byte-accounted, LRU-evicted past
    ``serve.stream.retainBytes``) or a result-cache reference (zero
    retained bytes — the cache already pins the table)."""
    global _RETAINED_BYTES
    if not token or not stream_id:
        return
    nb = 0
    if table is not None and cache_ref is None:
        try:
            nb = int(table.nbytes)
        except Exception:
            nb = 1 << 20
        if nb > _RETAIN_CAP:
            return
    key = (token, str(stream_id))
    with _RETAIN_LOCK:
        old = _RETAINED.pop(key, None)
        if old is not None:
            _RETAINED_BYTES -= old["nbytes"]
        _RETAINED[key] = {"table": None if cache_ref else table,
                          "cache_ref": cache_ref, "nbytes": nb}
        _RETAINED_BYTES += nb
        while _RETAINED_BYTES > _RETAIN_CAP and _RETAINED:
            _, ev = _RETAINED.popitem(last=False)
            _RETAINED_BYTES -= ev["nbytes"]
        _publish_retained_locked()


def _lookup_stream(token: Optional[str], stream_id: str):
    """The retained table for (token, stream_id), or None (evicted,
    acked, or never retained).  Cache-backed entries resolve through
    ``result_cache.peek`` — non-counting, so resume traffic does not
    inflate the hit-rate the zero-dispatch CI gate asserts on."""
    if not token:
        return None
    key = (token, str(stream_id))
    with _RETAIN_LOCK:
        ent = _RETAINED.get(key)
        if ent is not None:
            _RETAINED.move_to_end(key)
    if ent is None:
        return None
    if ent["cache_ref"] is not None:
        ck, names, stamps = ent["cache_ref"]
        return result_cache.peek(ck, names, stamps)
    return ent["table"]


def _release_stream(token: Optional[str], stream_id: str) -> bool:
    global _RETAINED_BYTES
    if not token:
        return False
    with _RETAIN_LOCK:
        ent = _RETAINED.pop((token, str(stream_id)), None)
        if ent is not None:
            _RETAINED_BYTES -= ent["nbytes"]
        _publish_retained_locked()
    return ent is not None


def retained_stats() -> Dict[str, int]:
    with _RETAIN_LOCK:
        return {"entries": len(_RETAINED), "bytes": _RETAINED_BYTES}


def clear_retained() -> None:
    global _RETAINED_BYTES
    with _RETAIN_LOCK:
        _RETAINED.clear()
        _RETAINED_BYTES = 0
        _publish_retained_locked()
    with _RESUME_LOCK:
        _RESUME_SESSIONS.clear()


class ServeSession:
    """Server-side client session: id, conf overlay, prepared
    statements, the fair-share in-flight gate, and the resume token a
    reconnecting client re-attaches with."""

    __slots__ = ("session_id", "priority", "timeout_ms",
                 "estimate_bytes", "max_inflight", "statements",
                 "inflight", "last_active", "created_unix", "closed",
                 "client_addr", "resume_token", "overlay", "_lock")

    def __init__(self, session_id: str, overlay: Dict[str, Any],
                 max_inflight: int, client_addr: str,
                 resume_token: Optional[str] = None):
        self.session_id = session_id
        self.overlay = dict(overlay or {})
        self.priority = int(self.overlay.get("priority", 0) or 0)
        t = self.overlay.get("timeoutMs")
        self.timeout_ms = int(t) if t else None
        e = self.overlay.get("estimateBytes")
        self.estimate_bytes = int(e) if e else None
        self.max_inflight = max(1, int(max_inflight))
        self.statements: Dict[str, PreparedStatement] = {}
        self.inflight = 0
        self.created_unix = time.time()
        self.last_active = time.monotonic()
        self.closed = False
        self.client_addr = client_addr
        self.resume_token = resume_token or os.urandom(12).hex()
        self._lock = threading.Lock()

    def touch(self) -> None:
        self.last_active = time.monotonic()

    def try_begin_query(self) -> str:
        """Atomically claim one fair-share slot: ``"ok"``, or the
        typed refusal — ``"closed"`` (the session was evicted; the
        caller answers SessionExpired) vs ``"full"`` (fair share;
        FairShareExceeded).  The tri-state closes the janitor race:
        eviction and admission serialize on the session lock, so a
        request can never slip a query into a session being torn
        down."""
        with self._lock:
            if self.closed:
                return "closed"
            if self.inflight >= self.max_inflight:
                return "full"
            self.inflight += 1
            return "ok"

    def end_query(self) -> None:
        with self._lock:
            self.inflight = max(0, self.inflight - 1)
            self.last_active = time.monotonic()

    def try_close_if_idle(self, idle_s: float) -> bool:
        """Janitor-side half of the eviction race fix: close only if
        nothing is in flight AND the idle clock expired, atomically
        under the same lock ``try_begin_query`` claims slots with.  An
        in-flight stream therefore always finishes before teardown;
        only NEW requests on an evicted session see SessionExpired."""
        with self._lock:
            if self.closed:
                return True
            if self.inflight > 0:
                return False
            if time.monotonic() - self.last_active <= idle_s:
                return False
            self.closed = True
            return True

    def force_close(self) -> None:
        with self._lock:
            self.closed = True

    def describe(self) -> Dict[str, Any]:
        return {"session_id": self.session_id,
                "priority": self.priority,
                "timeout_ms": self.timeout_ms,
                "estimate_bytes": self.estimate_bytes,
                "max_inflight": self.max_inflight,
                "inflight": self.inflight,
                "statements": sorted(self.statements),
                "client_addr": self.client_addr}


class _Inflight:
    """One query being answered on one connection: its future (None for
    a result-cache hit or a resumed stream) and the client-credit
    window."""

    def __init__(self, tag: int, future, credit: int,
                 template: Optional[str] = None):
        self.tag = tag
        self.future = future
        self._credit = max(0, int(credit))
        self._cv = threading.Condition()
        self.aborted = False
        self.abort_code: Optional[str] = None
        # SLO attribution: request receipt time + statement template
        # (None for ad-hoc sql / resumes) — e2e and first-chunk
        # latency observe against these at stream time
        self.t0_ns = time.monotonic_ns()
        self.template = template

    def add_credit(self, n: int) -> None:
        with self._cv:
            self._credit += max(0, int(n))
            self._cv.notify_all()

    def abort(self, code: Optional[str] = None) -> None:
        with self._cv:
            self.aborted = True
            if code and self.abort_code is None:
                self.abort_code = code
            self._cv.notify_all()

    def take_credit(self) -> bool:
        """Block until one CHUNK of credit is available; False when the
        stream aborted (disconnect/cancel/drain) or stalled out."""
        deadline = time.monotonic() + _STREAM_STALL_S
        with self._cv:
            while True:
                if self.aborted:
                    return False
                if self._credit > 0:
                    self._credit -= 1
                    return True
                if time.monotonic() >= deadline:
                    self.aborted = True
                    return False
                self._cv.wait(timeout=0.25)


class _ChunkFeed:
    """Per-flight relay of the leader stream's ENCODED result chunks.

    Single-flight followers used to block on the whole flight result and
    then re-chunk + re-encode it per follower; subscribing here instead
    lets a follower send chunk N the moment the leader's streamer has
    encoded it — follower first-chunk latency tracks the leader's (both
    observe ``slo.firstChunkMs``) and the Arrow slice+encode work is
    paid once per flight.  Payloads are buffered, so a follower joining
    mid-stream replays from chunk 1; a leader stream that dies before
    publishing everything aborts the feed and followers fall back to
    whole-result streaming from their own (settled) futures, resuming
    after the chunks already sent."""

    _STALL_S = 5.0

    def __init__(self):
        self._cond = threading.Condition()
        self._chunks: list = []
        self._done = False
        self._aborted = False
        self.rows = 0
        self.total = 0

    def publish(self, payload) -> None:
        with self._cond:
            if self._done or self._aborted:
                return
            self._chunks.append(payload)
            self._cond.notify_all()

    def finish(self, rows: int, total: int) -> None:
        with self._cond:
            if self._aborted:
                return
            self.rows, self.total = int(rows), int(total)
            self._done = True
            self._cond.notify_all()

    def abort(self) -> None:
        """No-op after finish(): the leader's error-path net calls this
        unconditionally."""
        with self._cond:
            if not self._done:
                self._aborted = True
            self._cond.notify_all()

    def next(self, i: int) -> Tuple[str, Any]:
        """('chunk', payload) for index ``i``, ('done', None) past the
        final chunk, ('abort', None) on a dead or stalled leader."""
        with self._cond:
            self._cond.wait_for(
                lambda: i < len(self._chunks) or self._done
                or self._aborted,
                timeout=self._STALL_S)
            if i < len(self._chunks):
                return "chunk", self._chunks[i]
            if self._done:
                return "done", None
            return "abort", None


class _Conn:
    __slots__ = ("sock", "wlock", "addr", "alive", "session",
                 "inflight", "closed_cleanly", "streamers", "_lock")

    def __init__(self, sock: socket.socket, addr: str):
        self.sock = sock
        self.wlock = threading.Lock()
        self.addr = addr
        self.alive = True
        self.session: Optional[ServeSession] = None
        self.inflight: Dict[int, _Inflight] = {}
        self.closed_cleanly = False
        self.streamers: list = []
        self._lock = threading.Lock()

    def track(self, infl: _Inflight) -> None:
        with self._lock:
            self.inflight[infl.tag] = infl

    def untrack(self, tag: int) -> None:
        with self._lock:
            self.inflight.pop(tag, None)

    def take_all(self) -> list:
        with self._lock:
            out = list(self.inflight.values())
            self.inflight.clear()
        return out

    def add_streamer(self, t: threading.Thread) -> None:
        with self._lock:
            self.streamers = [s for s in self.streamers
                              if s.is_alive()] + [t]

    def live_streamers(self) -> list:
        with self._lock:
            return [s for s in self.streamers if s.is_alive()]


class ServeServer:
    """See module docstring.  One per engine session; ``shutdown()`` is
    idempotent and also fires when the engine session is collected."""

    def __init__(self, session, port: Optional[int] = None):
        import hashlib

        from spark_rapids_tpu import config as cfg
        global _RETAIN_CAP
        conf = session.conf
        self._engine_ref = weakref.ref(session)
        # semantics stamp: the engine session's result-affecting SQL
        # configuration participates in every result-cache key, so a
        # later session in the same process with different semantics
        # knobs (float-agg ordering, incompat ops, cast behavior…) can
        # never be served a result this session computed — the cache
        # itself is process-global.  Over-invalidation (a knob that
        # doesn't really change results) only costs a miss.
        sql_conf = sorted((k, repr(v)) for k, v in
                          conf._settings.items()
                          if k.startswith("spark.rapids.tpu.sql"))
        self._semantics_stamp = hashlib.sha1(
            repr(sql_conf).encode()).hexdigest()[:16]
        self._max_inflight = int(conf.get(cfg.SERVE_SESSION_MAX_INFLIGHT))
        self._idle_timeout_s = max(
            0.05, int(conf.get(cfg.SERVE_SESSION_IDLE_TIMEOUT_MS)) / 1e3)
        self._chunk_rows = max(
            1, int(conf.get(cfg.SERVE_STREAM_CHUNK_ROWS)))
        self._max_frame_bytes = max(
            1 << 10, int(conf.get(cfg.SERVE_WIRE_MAX_FRAME_BYTES)))
        self._read_timeout_s = max(
            0.05, int(conf.get(cfg.SERVE_WIRE_READ_TIMEOUT_MS)) / 1e3)
        self._write_stall_s = max(
            0.05, int(conf.get(cfg.SERVE_WIRE_WRITE_STALL_MS)) / 1e3)
        self._storm_threshold = max(
            1, int(conf.get(cfg.SERVE_WIRE_STORM_THRESHOLD)))
        self._drain_deadline_ms = max(
            0, int(conf.get(cfg.SERVE_DRAIN_DEADLINE_MS)))
        _RETAIN_CAP = max(0, int(conf.get(cfg.SERVE_STREAM_RETAIN_BYTES)))
        # seeded chaos plan for this server's lifetime (fresh=True:
        # a restarted server re-arms the same spec rather than
        # inheriting an exhausted schedule)
        serve_faults.install_plan_from_conf(conf, fresh=True)
        result_cache.configure(
            bool(conf.get(cfg.SERVE_RESULT_CACHE_ENABLED)),
            int(conf.get(cfg.SERVE_RESULT_CACHE_MAX_BYTES)))
        # incremental result maintenance (exec/incremental.py): delta
        # scans + retained aggregate partials over the result cache,
        # plus the background stamp-polling refresher
        from spark_rapids_tpu.exec.incremental import \
            IncrementalMaintainer
        self.maintainer = IncrementalMaintainer(session)
        # micro-batched prepared-statement dispatch (serve/batching.py);
        # None when serve.batch.enabled is off — the one-knob revert
        self._batcher = None
        if bool(conf.get(cfg.SERVE_BATCH_ENABLED)):
            from spark_rapids_tpu.serve.batching import StatementBatcher
            self._batcher = StatementBatcher(
                self, int(conf.get(cfg.SERVE_BATCH_WINDOW_MS)),
                int(conf.get(cfg.SERVE_BATCH_MAX_STATEMENTS)))
        # token auth: non-empty allowlist means every hello must carry
        # a matching auth_token or the connection gets a typed
        # AuthFailed ERR before any session exists
        self._auth_tokens = frozenset(
            t.strip() for t in
            str(conf.get(cfg.SERVE_AUTH_TOKENS) or "").split(",")
            if t.strip())
        # optional TLS: both PEM paths or neither — exactly one is a
        # misconfiguration that must not silently serve plaintext
        cert = str(conf.get(cfg.SERVE_TLS_CERT_FILE) or "").strip()
        key = str(conf.get(cfg.SERVE_TLS_KEY_FILE) or "").strip()
        self._ssl_ctx = None
        if bool(cert) != bool(key):
            raise ValueError(
                "serve.tls.certFile and serve.tls.keyFile must be set "
                "together (exactly one is set)")
        if cert:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=cert, keyfile=key)
            self._ssl_ctx = ctx
        # fleet store (attached by api/session.py when fleet.enabled):
        # prepared-statement specs publish here so ANY replica can
        # re-materialize a statement it never prepared — the router's
        # failover replay and cross-replica execute both lean on it
        self._store = getattr(session, "fleet_store", None)
        # statement ids carry a per-process nonce once a fleet store is
        # attached: two replicas both minting "stmt-00001" would alias
        # in the shared registry.  Storeless servers keep the legacy
        # format (the one-knob-revert byte-for-byte contract).
        self._stmt_nonce = os.urandom(3).hex() \
            if self._store is not None else ""
        self._sessions: Dict[str, ServeSession] = {}
        self._lock = threading.Lock()
        self._session_seq = itertools.count(1)
        self._stmt_seq = itertools.count(1)
        self._stop = threading.Event()
        self._draining = False
        self._drained = threading.Event()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._streamer_count = 0
        self._malformed = 0
        self._storm_dumped = False
        host = str(conf.get(cfg.SERVE_HOST))
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        bind_port = int(port if port is not None
                        else conf.get(cfg.SERVE_PORT))
        self._lsock.bind((host, bind_port))
        self._lsock.listen(128)
        self.host = host
        self.port = self._lsock.getsockname()[1]
        reg = obsreg.get_registry()
        reg.set_gauge("serve.connections", 0)
        reg.set_gauge("serve.streamerThreads", 0)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"serve-accept-{self.port}",
            daemon=True)
        self._accept_thread.start()
        self._janitor = threading.Thread(
            target=self._janitor_loop, name=f"serve-janitor-{self.port}",
            daemon=True)
        self._janitor.start()
        self._finalizer = weakref.finalize(
            session, ServeServer._static_shutdown, self._lsock,
            self._stop)

    # -- lifecycle ---------------------------------------------------------
    @staticmethod
    def _static_shutdown(lsock, stop) -> None:
        stop.set()
        # shutdown() before close(): a thread blocked in accept() holds
        # an in-syscall reference that keeps the LISTEN socket — and the
        # port — alive past close(); shutdown wakes it so a successor
        # can rebind immediately
        try:
            lsock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            lsock.close()
        except OSError:
            pass

    def shutdown(self) -> None:
        self._draining = True
        if self._batcher is not None:
            self._batcher.flush_all()
        self._static_shutdown(self._lsock, self._stop)
        self.maintainer.shutdown()
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for s in sessions:
            s.force_close()
        # release the materialized results: the cache is process-global
        # and would otherwise pin up to its whole byte budget of
        # pa.Tables after the serving session is gone (the semantics
        # stamp already guarantees a later session can't be served
        # stale semantics; this is purely about memory).  The retained
        # stream window goes with it — full shutdown, unlike drain(),
        # means no process-local successor will answer a resume.
        result_cache.clear()
        clear_retained()
        reg = obsreg.get_registry()
        reg.set_gauge("serve.activeSessions", 0)
        reg.set_gauge("serve.connections", 0)
        reg.set_gauge("serve.streamerThreads", 0)

    def drain(self, deadline_ms: Optional[int] = None) -> Dict[str, Any]:
        """Graceful shutdown preserving resume state: stop accepting,
        refuse new work with a typed ``Draining`` error, let in-flight
        streams finish inside the deadline, cancel stragglers with a
        typed abort, join every streamer thread, release every
        admission slot and credit window, close every connection.
        Resume tokens, the retained-stream window and the result cache
        survive — a successor ``ServeServer`` on the same port (see
        ``session.restart_serve_server``) answers re-hellos and
        resume_stream requests as if the drain never happened."""
        if deadline_ms is None:
            deadline_ms = self._drain_deadline_ms
        already = self._draining
        self._draining = True
        if already and self._drained.is_set():
            return {"drained": True, "cancelled": 0, "already": True}
        reg = obsreg.get_registry()
        reg.inc("serve.drains")
        obsrec.record_event("serve.drainStarted", port=self.port,
                            deadline_ms=deadline_ms)
        # parked batch windows flush NOW: their items hold fair-share
        # slots the phase-1 wait below watches
        if self._batcher is not None:
            self._batcher.flush_all()
        # shutdown() wakes a blocked accept(); without it the accept
        # thread's in-syscall reference keeps the port bound and the
        # successor server's bind fails with EADDRINUSE
        try:
            self._lsock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._lsock.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=2.0)
        # phase 1: wait for in-flight streams to finish on their own
        deadline = time.monotonic() + max(0, int(deadline_ms)) / 1e3
        while time.monotonic() < deadline:
            with self._conns_lock:
                busy = any(c.inflight for c in self._conns)
            if not busy:
                break
            time.sleep(0.02)
        # phase 2: cancel stragglers with the typed Draining abort (the
        # streamer's last act on a live socket is an ERR the client can
        # key its reconnect-and-resume on)
        with self._conns_lock:
            conns = list(self._conns)
        cancelled = 0
        for conn in conns:
            for infl in conn.take_all():
                infl.abort("Draining")
                if infl.future is not None:
                    infl.future.cancel("server draining")
                cancelled += 1
        # phase 3: leak-audited teardown — join every streamer before
        # declaring the drain done, so "zero streamer threads" is a
        # fact, not a hope
        for conn in conns:
            for t in conn.live_streamers():
                t.join(timeout=10.0)
        self._stop.set()
        for conn in conns:
            conn.alive = False
            conn.closed_cleanly = True
            try:
                conn.sock.close()
            except OSError:
                pass
        # reader threads unregister themselves on exit; wait for the
        # registry to empty so "drained" implies a clean leak audit
        # rather than racing the last thread's finally block
        conn_deadline = time.monotonic() + 2.0
        while time.monotonic() < conn_deadline:
            with self._conns_lock:
                if not self._conns:
                    break
            time.sleep(0.01)
        self._janitor.join(timeout=2.0)
        self.maintainer.shutdown()
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for s in sessions:
            # closed for NEW work on this instance; the resume-token
            # registry (_register_resume at hello) lets a successor
            # re-mint an equivalent session
            s.force_close()
        reg.set_gauge("serve.activeSessions", 0)
        reg.set_gauge("serve.connections", 0)
        reg.set_gauge("serve.streamerThreads", 0)
        self._drained.set()
        obsrec.record_event("serve.drainFinished", port=self.port,
                            cancelled=cancelled)
        return {"drained": True, "cancelled": cancelled}

    def leak_stats(self) -> Dict[str, int]:
        """Live leak-audit counters (tests + the CI chaos gate assert
        these return to zero after drain)."""
        with self._conns_lock:
            conns = list(self._conns)
            streamers = self._streamer_count
        return {"connections": len(conns),
                "streamer_threads": streamers,
                "inflight": sum(len(c.inflight) for c in conns),
                "sessions": len(self.sessions()),
                "retained_streams": retained_stats()["entries"],
                "retained_bytes": retained_stats()["bytes"]}

    def state(self) -> str:
        """Lifecycle state for /healthz: ``serving`` → ``draining`` →
        ``drained``.  The fleet router polls this to take a replica
        out of placement rotation BEFORE it stops answering."""
        if self._drained.is_set():
            return "drained"
        if self._draining:
            return "draining"
        return "serving"

    def inflight_count(self) -> int:
        with self._conns_lock:
            return sum(len(c.inflight) for c in self._conns)

    def _engine(self):
        eng = self._engine_ref()
        if eng is None:
            raise ServeError("ServerStopping",
                             "engine session gone; server stopping")
        return eng

    # -- session registry --------------------------------------------------
    def sessions(self) -> Dict[str, ServeSession]:
        with self._lock:
            return dict(self._sessions)

    def _publish_sessions(self) -> None:
        obsreg.get_registry().set_gauge("serve.activeSessions",
                                        len(self._sessions))

    def _open_session(self, overlay: Dict[str, Any], addr: str,
                      resume_token: Optional[str] = None) -> ServeSession:
        sid = f"s-{next(self._session_seq):05d}"
        sess = ServeSession(sid, overlay or {}, self._max_inflight, addr,
                            resume_token=resume_token)
        with self._lock:
            self._sessions[sid] = sess
            self._publish_sessions()
        _register_resume(sess.resume_token, sess.overlay)
        reg = obsreg.get_registry()
        reg.inc("serve.sessions")
        obsrec.record_event("serve.sessionOpened", session=sid,
                            client_addr=addr,
                            resumed=resume_token is not None)
        return sess

    def _evict(self, sess: ServeSession, reason: str) -> None:
        with self._lock:
            cur = self._sessions.get(sess.session_id)
            if cur is not sess:
                return
            del self._sessions[sess.session_id]
            self._publish_sessions()
        sess.force_close()
        obsreg.get_registry().inc("serve.sessionsEvicted")
        obsrec.record_event("serve.sessionEvicted",
                            session=sess.session_id, reason=reason)

    def _janitor_loop(self) -> None:
        interval = min(2.0, max(0.02, self._idle_timeout_s / 4))
        while not self._stop.wait(interval):
            for sess in list(self.sessions().values()):
                # the close decision is atomic with slot admission
                # (ServeSession.try_close_if_idle), so a session with a
                # query still streaming is never torn down under it
                if sess.try_close_if_idle(self._idle_timeout_s):
                    self._evict(sess, "idle-timeout")

    # -- accept / per-connection reader ------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = self._lsock.accept()
            except OSError:
                return
            wire.set_low_latency(sock)
            ev = serve_faults.check("accept")
            if ev is not None:
                if ev.action is ServeFaultAction.CLOSE:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    continue
                if ev.action is ServeFaultAction.DELAY:
                    time.sleep(ev.delay_s)
            threading.Thread(
                target=self._serve_conn,
                args=(sock, f"{addr[0]}:{addr[1]}"),
                name=f"serve-conn-{addr[1]}", daemon=True).start()

    def _register_conn(self, conn: _Conn) -> None:
        with self._conns_lock:
            self._conns.add(conn)
            obsreg.get_registry().set_gauge("serve.connections",
                                            len(self._conns))

    def _unregister_conn(self, conn: _Conn) -> None:
        with self._conns_lock:
            self._conns.discard(conn)
            obsreg.get_registry().set_gauge("serve.connections",
                                            len(self._conns))

    def _note_malformed(self, conn: _Conn, reason: str) -> None:
        reg = obsreg.get_registry()
        reg.inc("serve.wire.malformedFrames")
        reg.inc(f"serve.wire.malformedFrames.{reason}")
        obsrec.record_event("serve.malformedFrame", reason=reason,
                            client=conn.addr)
        with self._lock:
            self._malformed += 1
            storm = (self._malformed >= self._storm_threshold
                     and not self._storm_dumped)
            if storm:
                self._storm_dumped = True
        if storm:
            rec = obsrec.get_recorder()
            if rec is not None:
                try:
                    rec.dump_bundle(None, reason="protocol")
                except Exception:
                    pass

    def _serve_conn(self, sock: socket.socket, addr: str) -> None:
        if self._ssl_ctx is not None:
            # handshake on the per-connection thread (never the accept
            # loop — a stalled handshake must not block other accepts),
            # under the frame-progress deadline as its time bound
            try:
                sock.settimeout(self._read_timeout_s)
                sock = self._ssl_ctx.wrap_socket(sock, server_side=True)
            except (OSError, ValueError) as e:
                obsreg.get_registry().inc("serve.tlsHandshakeFailures")
                obsrec.record_event("serve.tlsHandshakeFailed",
                                    client=addr, error=str(e))
                try:
                    sock.close()
                except OSError:
                    pass
                return
        conn = _Conn(sock, addr)
        self._register_conn(conn)
        try:
            sock.settimeout(_TICK)
        except OSError:
            pass
        try:
            while not self._stop.is_set():
                try:
                    frame = wire.read_frame(
                        sock, max_frame_bytes=self._max_frame_bytes,
                        frame_timeout_s=self._read_timeout_s)
                except wire.ServeWireError as e:
                    if not conn.alive or self._stop.is_set():
                        return
                    self._note_malformed(conn, e.reason)
                    if e.reason in ("unknownKind", "badPayload"):
                        # frame boundary intact: answer and keep going
                        self._send_err(conn, getattr(e, "tag", 0),
                                       "ProtocolError", str(e),
                                       reason=e.reason)
                        continue
                    # oversized / truncated / timeout desync or kill
                    # the stream: best-effort typed ERR, then close
                    self._send_err(conn, 0, "ProtocolError", str(e),
                                   reason=e.reason)
                    return
                if frame is wire.IDLE:
                    continue
                if frame is None:
                    return
                kind, tag, payload = frame
                if kind == wire.CREDIT:
                    try:
                        msg = wire.decode_msg(payload)
                    except wire.ServeWireError as e:
                        self._note_malformed(conn, e.reason)
                        self._send_err(conn, tag, "ProtocolError",
                                       str(e), reason=e.reason)
                        continue
                    infl = conn.inflight.get(tag)
                    if infl is not None:
                        infl.add_credit(int(msg.get("n", 1)))
                elif kind == wire.REQ:
                    try:
                        msg = wire.decode_msg(payload)
                    except wire.ServeWireError as e:
                        self._note_malformed(conn, e.reason)
                        self._send_err(conn, tag, "ProtocolError",
                                       str(e), reason=e.reason)
                        continue
                    if not self._handle_request(conn, tag, msg):
                        return
                else:
                    # well-formed frame of a kind a client must never
                    # send (RESP/CHUNK/ERR/END): typed refusal, and the
                    # stream is still in sync so the connection lives
                    self._note_malformed(conn, "unknownKind")
                    self._send_err(conn, tag, "ProtocolError",
                                   f"unexpected frame kind {kind} "
                                   f"from client", reason="unknownKind")
        except wire.WireError:
            pass
        finally:
            self._on_disconnect(conn)
            self._unregister_conn(conn)
            try:
                sock.close()
            except OSError:
                pass

    def _on_disconnect(self, conn: _Conn) -> None:
        conn.alive = False
        pending = conn.take_all()
        for infl in pending:
            infl.abort()
            if infl.future is not None:
                infl.future.cancel("client disconnected")
        if conn.session is not None:
            conn.session.touch()
        if not conn.closed_cleanly:
            obsreg.get_registry().inc("serve.clientDisconnects")
            if pending:
                obsrec.record_event(
                    "serve.disconnectCancelled",
                    session=getattr(conn.session, "session_id", None),
                    cancelled=len(pending))

    # -- request dispatch --------------------------------------------------
    def _send_resp(self, conn: _Conn, tag: int,
                   obj: Dict[str, Any]) -> None:
        wire.send_frame(conn.sock, conn.wlock, wire.RESP, tag,
                        wire.encode_msg(obj),
                        stall_s=self._write_stall_s)

    def _send_err(self, conn: _Conn, tag: int, code: str, msg: str,
                  reason: Optional[str] = None) -> None:
        obj: Dict[str, Any] = {"type": code, "error": msg}
        if reason:
            obj["reason"] = reason
        try:
            wire.send_frame(conn.sock, conn.wlock, wire.ERR, tag,
                            wire.encode_msg(obj),
                            stall_s=self._write_stall_s)
        except wire.WireError:
            pass

    def _handle_request(self, conn: _Conn, tag: int,
                        msg: Dict[str, Any]) -> bool:
        """Dispatch one REQ; returns False when the connection should
        close (the ``close`` op)."""
        op = str(msg.get("op", ""))
        reg = obsreg.get_registry()
        reg.inc("serve.requests")
        try:
            if self._draining and op in ("hello", "sql", "prepare",
                                         "execute", "resume_stream"):
                raise ServeError(
                    "Draining",
                    "server is draining; reconnect and resume shortly")
            if op == "hello":
                self._handle_hello(conn, tag, msg)
                return True
            if op == "ping":
                self._send_resp(conn, tag, {"ok": True})
                return True
            if op == "close":
                conn.closed_cleanly = True
                if conn.session is not None and \
                        bool(msg.get("end_session", True)):
                    self._evict(conn.session, "client-close")
                self._send_resp(conn, tag, {"ok": True})
                return False
            sess = self._session_of(conn)
            sess.touch()
            if op == "sql":
                plan = self._parse(str(msg.get("sql", "")))
                self._start_query(conn, tag, sess, plan,
                                  int(msg.get("credit", 8)),
                                  stream_id=msg.get("stream_id"))
            elif op == "prepare":
                stmt = self._prepare(sess, msg)
                self._send_resp(conn, tag, stmt.describe())
            elif op == "execute":
                stmt = self._statement_of(sess, msg)
                if self._batcher is not None and \
                        self._batcher.offer(conn, tag, sess, stmt, msg):
                    pass   # parked in the batching window; flush answers
                else:
                    plan = stmt.bind(msg.get("params") or {})
                    self._start_query(conn, tag, sess, plan,
                                      int(msg.get("credit", 8)),
                                      stream_id=msg.get("stream_id"),
                                      template=stmt.sql)
            elif op == "resume_stream":
                self._start_resume(conn, tag, sess, msg)
            elif op == "finish_stream":
                released = _release_stream(
                    sess.resume_token, str(msg.get("stream_id", "")))
                self._send_resp(conn, tag, {"ok": True,
                                            "released": released})
            elif op == "close_statement":
                sid = str(msg.get("statement_id", ""))
                sess.statements.pop(sid, None)
                self._send_resp(conn, tag, {"ok": True})
            elif op == "cancel":
                target = int(msg.get("request", -1))
                infl = conn.inflight.get(target)
                cancelled = False
                if infl is not None:
                    infl.abort()
                    if infl.future is not None:
                        cancelled = infl.future.cancel(
                            "cancelled by client")
                self._send_resp(conn, tag, {"cancelled": cancelled})
            elif op == "session_info":
                self._send_resp(conn, tag, sess.describe())
            else:
                raise ServeError("UnknownOp",
                                 f"unknown request op {op!r}")
        except ServeError as e:
            self._send_err(conn, tag, e.code, str(e))
        except StatementError as e:
            self._send_err(conn, tag, "StatementError", str(e))
        except wire.WireError:
            raise
        except Exception as e:
            self._send_err(conn, tag, type(e).__name__, str(e))
        return True

    def _handle_hello(self, conn: _Conn, tag: int,
                      msg: Dict[str, Any]) -> None:
        if self._auth_tokens:
            presented = str(msg.get("auth_token") or "")
            if presented not in self._auth_tokens:
                obsreg.get_registry().inc("serve.authFailures")
                obsrec.record_event("serve.authFailed",
                                    client=conn.addr,
                                    presented=bool(presented))
                raise ServeError(
                    "AuthFailed",
                    "hello rejected: missing or unknown auth_token "
                    "(serve.auth.tokens)")
        token = str(msg.get("resume") or "") or None
        sess: Optional[ServeSession] = None
        resumed = False
        if token:
            with self._lock:
                for cand in self._sessions.values():
                    if cand.resume_token == token and not cand.closed:
                        sess = cand
                        break
            if sess is not None:
                resumed = True       # live re-attach: statements intact
            else:
                overlay = _resume_overlay(token)
                if overlay is not None:
                    # the original session is gone (evicted or drained)
                    # but the token is known: mint an equivalent session
                    # under the SAME token; the client replays prepared
                    # statements it still holds text for
                    sess = self._open_session(overlay, conn.addr,
                                              resume_token=token)
                    resumed = True
        if sess is None:
            sess = self._open_session(msg.get("conf") or {}, conn.addr)
        conn.session = sess
        sess.touch()
        self._send_resp(conn, tag, {
            "session_id": sess.session_id,
            "protocol": wire.PROTOCOL_VERSION,
            "engine": "spark-rapids-tpu",
            "resume_token": sess.resume_token,
            "resumed": resumed,
            "statements": sorted(sess.statements)})

    def _session_of(self, conn: _Conn) -> ServeSession:
        sess = conn.session
        if sess is None:
            raise ServeError("NoSession",
                             "send a hello request before queries")
        ev = serve_faults.check("session.lookup")
        if ev is not None and ev.action is ServeFaultAction.FAIL:
            raise ServeError(
                "SessionExpired",
                f"session {sess.session_id} lookup failed "
                f"(fault injection); re-hello with your resume token")
        if sess.closed or sess.session_id not in self.sessions():
            raise ServeError(
                "SessionExpired",
                f"session {sess.session_id} was evicted "
                f"(idle > {self._idle_timeout_s:.1f}s or closed); "
                f"send a new hello")
        return sess

    def _statement_of(self, sess: ServeSession,
                      msg: Dict[str, Any]) -> PreparedStatement:
        sid = str(msg.get("statement_id", ""))
        stmt = sess.statements.get(sid)
        if stmt is None and self._store is not None:
            stmt = self._statement_from_store(sess, sid)
        if stmt is None:
            raise ServeError("UnknownStatement",
                             f"no prepared statement {sid!r} in "
                             f"session {sess.session_id}")
        return stmt

    def _statement_from_store(self, sess: ServeSession,
                              sid: str) -> Optional[PreparedStatement]:
        """Re-materialize a statement a SIBLING replica prepared: the
        fleet's shared statement-template registry means an execute
        routed (or failed over) to a replica that never saw the prepare
        still resolves the id."""
        import json as _json
        if not sid:
            return None
        try:
            raw = self._store.get("stmt", sid)
            if raw is None:
                return None
            spec = _json.loads(raw.decode("utf-8"))
            stmt = PreparedStatement(sid, str(spec["sql"]),
                                     spec.get("declared_types") or {},
                                     self._engine().catalog)
        except Exception:
            return None
        sess.statements[sid] = stmt
        obsreg.get_registry().inc("serve.statementsAdopted")
        return stmt

    def _parse(self, sql: str):
        if not sql.strip():
            raise ServeError("EmptyStatement", "empty sql")
        from spark_rapids_tpu.sql import parse_sql
        return parse_sql(sql, self._engine().catalog)

    def _prepare(self, sess: ServeSession,
                 msg: Dict[str, Any]) -> PreparedStatement:
        sql = str(msg.get("sql", ""))
        if not sql.strip():
            raise ServeError("EmptyStatement", "empty sql")
        nonce = f"{self._stmt_nonce}-" if self._stmt_nonce else ""
        stmt_id = f"stmt-{nonce}{next(self._stmt_seq):05d}"
        stmt = PreparedStatement(stmt_id, sql, msg.get("params") or {},
                                 self._engine().catalog)
        sess.statements[stmt_id] = stmt
        obsreg.get_registry().inc("serve.statementsPrepared")
        if self._store is not None:
            import json as _json
            try:
                self._store.put("stmt", stmt_id, _json.dumps(
                    {"sql": stmt.sql,
                     "declared_types": dict(stmt.declared_types)}
                ).encode("utf-8"))
            except Exception:
                obsreg.get_registry().inc("fleet.store.errors")
        return stmt

    # -- query execution + streaming ---------------------------------------
    def _begin_or_raise(self, sess: ServeSession) -> None:
        state = sess.try_begin_query()
        if state == "closed":
            raise ServeError(
                "SessionExpired",
                f"session {sess.session_id} was closed; "
                f"re-hello with your resume token")
        if state != "ok":
            raise ServeError(
                "FairShareExceeded",
                f"session {sess.session_id} already has "
                f"{sess.max_inflight} queries in flight "
                f"(serve.session.maxInFlight)")

    def _spawn_streamer(self, conn: _Conn, tag: int, target,
                        args: tuple) -> None:
        with self._conns_lock:
            self._streamer_count += 1
            obsreg.get_registry().set_gauge("serve.streamerThreads",
                                            self._streamer_count)

        def run() -> None:
            try:
                target(*args)
            finally:
                with self._conns_lock:
                    self._streamer_count -= 1
                    obsreg.get_registry().set_gauge(
                        "serve.streamerThreads", self._streamer_count)

        t = threading.Thread(target=run, name=f"serve-stream-{tag}",
                             daemon=True)
        conn.add_streamer(t)
        t.start()

    def _start_query(self, conn: _Conn, tag: int, sess: ServeSession,
                     plan, credit: int,
                     stream_id: Optional[str] = None,
                     template: Optional[str] = None) -> None:
        self._begin_or_raise(sess)
        try:
            digest = cache_key = names = stamps = None
            cacheable = False
            fp_cacheable = False
            submit_plan, inc_ctx = plan, None
            try:
                from spark_rapids_tpu.exec import incremental
                from spark_rapids_tpu.plan.digest import plan_fingerprint
                fp = plan_fingerprint(plan)
                digest = fp.digest
                fp_cacheable = fp.cacheable
                # cache entries key on (semantics stamp, plan digest):
                # the profile//queries surface the pure digest, the
                # cache must also see the session's SQL conf
                cache_key = f"{self._semantics_stamp}:{fp.digest}"
                names = tuple(plan.schema.names)
                if fp.cacheable and result_cache.enabled():
                    # stamps come from the LIVE expansion of the scan's
                    # source roots (not the frozen read()-time file
                    # list) so a file appended to a watched dataset
                    # invalidates — and delta-refreshes — the entry
                    stamps = incremental.current_stamps(plan)
                    cacheable = stamps is not None
            except Exception:
                cacheable = False
            if cacheable:
                # miss counting is deferred to after submission: a miss
                # that joins an in-flight single-flight execution is a
                # dedup, not a second miss
                hit = result_cache.lookup(cache_key, names, stamps,
                                          count_miss=False)
                if hit is not None:
                    # ledger: a cache hit never passes the scheduler,
                    # so the tenant is charged directly (same name as
                    # the global counter result_cache.lookup bumped)
                    from spark_rapids_tpu.obs import accounting as acct
                    acct.charge_tenant(sess.session_id, template,
                                       digest,
                                       "serve.resultCacheHits", 1)
                    infl = _Inflight(tag, None, credit,
                                     template=template)
                    conn.track(infl)
                    self._spawn_streamer(
                        conn, tag, self._stream_cached,
                        (conn, sess, infl, hit, stream_id,
                         (cache_key, names, stamps)))
                    return
                # incremental maintenance decides full-capture vs delta
                # (and re-pins watched scans to the live file set so
                # the executed plan reads what the stamps describe)
                submit_plan, inc_ctx = self.maintainer.prepare(
                    plan, cache_key, names, stamps)
            eng = self._engine()
            meta = {"session_id": sess.session_id,
                    "client_addr": sess.client_addr}
            if template is not None:
                meta["statement_template"] = template
            if digest is not None:
                meta["plan_digest"] = digest  # already computed here
                meta["plan_cacheable"] = fp_cacheable
            if inc_ctx is not None and inc_ctx.mode == "delta":
                # a delta run merges retained partials in finish();
                # fanning one execution to two delta contexts would
                # double-merge — delta runs never join a flight
                meta["no_dedup"] = True
            fut = eng.scheduler.submit(
                submit_plan, priority=sess.priority,
                timeout_ms=sess.timeout_ms,
                estimate_bytes=sess.estimate_bytes,
                meta=meta)
            is_follower = getattr(fut, "dedup_of", None) is not None
            if not is_follower and getattr(fut, "_flight", None) \
                    is not None:
                # flight leader: install the chunk relay BEFORE the
                # streamer spawns, so every follower joining after this
                # point finds it (a follower racing this install just
                # takes the whole-result path — slower, never wrong)
                fut._flight.chunk_feed = _ChunkFeed()
            if cacheable:
                miss_name = ("serve.resultCacheDedupedFollowers"
                             if is_follower
                             else "serve.resultCacheMisses")
                obsreg.get_registry().inc(miss_name)
                from spark_rapids_tpu.obs import accounting as acct
                acct.charge_tenant(sess.session_id, template, digest,
                                   miss_name, 1)
            infl = _Inflight(tag, fut, credit, template=template)
            conn.track(infl)
            self._spawn_streamer(
                conn, tag, self._stream_result,
                (conn, sess, infl, cache_key, names, stamps,
                 cacheable and not is_follower, plan,
                 None if is_follower else inc_ctx, stream_id))
        except BaseException:
            sess.end_query()
            raise

    def _start_resume(self, conn: _Conn, tag: int, sess: ServeSession,
                      msg: Dict[str, Any]) -> None:
        stream_id = str(msg.get("stream_id", ""))
        after_seq = max(0, int(msg.get("after_seq", 0)))
        credit = int(msg.get("credit", 8))
        if not stream_id:
            raise ServeError("BadRequest",
                             "resume_stream requires stream_id")
        table = _lookup_stream(sess.resume_token, stream_id)
        if table is None:
            raise ServeError(
                "ResumeUnavailable",
                f"no retained stream {stream_id!r} for this session; "
                f"re-execute the original request")
        self._begin_or_raise(sess)
        reg = obsreg.get_registry()
        reg.inc("serve.resumedStreams")
        obsrec.record_event("serve.streamResumed",
                            session=sess.session_id,
                            stream_id=stream_id, after_seq=after_seq)
        infl = _Inflight(tag, None, credit)
        conn.track(infl)
        release = self._releaser(conn, sess, infl)

        def run() -> None:
            try:
                self._stream_table(conn, infl, table, cache_hit=True,
                                   query_id=None, release=release,
                                   after_seq=after_seq)
            finally:
                release()

        self._spawn_streamer(conn, tag, run, ())

    @staticmethod
    def _releaser(conn: _Conn, sess: ServeSession, infl: _Inflight):
        """Once-only release of the query's fair-share slot + in-flight
        tracking.  Called just BEFORE the END frame goes out (so a
        client that pipelines its next query the instant END arrives
        can never race a still-held slot into FairShareExceeded) and
        again from the streamer's finally as the error-path net."""
        done = threading.Event()

        def release() -> None:
            if not done.is_set():
                done.set()
                conn.untrack(infl.tag)
                sess.end_query()
        return release

    def _stream_cached(self, conn: _Conn, sess: ServeSession,
                       infl: _Inflight, table,
                       stream_id: Optional[str],
                       cache_ref: Optional[Tuple]) -> None:
        release = self._releaser(conn, sess, infl)
        try:
            # a cache-backed retention costs zero retained bytes: the
            # cache already pins the table, resume peeks it by key
            _retain_stream(sess.resume_token, stream_id,
                           cache_ref=cache_ref)
            self._stream_table(conn, infl, table, cache_hit=True,
                               query_id=None, release=release)
        finally:
            release()

    def _stream_result(self, conn: _Conn, sess: ServeSession,
                       infl: _Inflight, cache_key, names, stamps,
                       cacheable: bool, plan=None, inc_ctx=None,
                       stream_id: Optional[str] = None) -> None:
        fut = infl.future
        release = self._releaser(conn, sess, infl)
        reg = obsreg.get_registry()
        feed = fed = None
        fl = getattr(fut, "_flight", None)
        is_leader = fl is not None and \
            getattr(fut, "dedup_of", None) is None
        if fl is not None and not is_leader:
            # follower: subscribe per-chunk to the leader stream's feed.
            # Nothing is retained for resume while the feed streams — a
            # disconnect mid-feed resolves as ResumeUnavailable and the
            # client re-executes from last_seq (its sequence filter
            # keeps the replay duplicate-free), trading the rare
            # disconnect's cost for first-chunk latency that tracks the
            # leader chunk-for-chunk
            feed = fl.chunk_feed
        try:
            if feed is not None:
                reg.inc("serve.dedup.chunkFeedStreams")
                status, fed = self._stream_from_feed(conn, infl, feed,
                                                     fut.query_id,
                                                     release)
                if status in ("done", "dead"):
                    return
                # leader stream died or stalled before finishing: fall
                # back to whole-result streaming off this follower's own
                # future, resuming after the chunks already sent
                reg.inc("serve.dedup.chunkFeedFallbacks")
            try:
                table = fut.result()
            except BaseException as e:
                # a live connection always gets a terminal frame (an
                # explicitly cancelled stream included — only a dead
                # socket goes unanswered), or the client would wait on
                # a stream that will never end.  A drain-cancelled
                # query reports the typed Draining code the client's
                # reconnect-and-resume keys on.
                if conn.alive:
                    self._send_err(conn, infl.tag,
                                   infl.abort_code or type(e).__name__,
                                   str(e))
                return
            if inc_ctx is not None:
                # the maintainer owns caching for maintained runs
                # (result + partial state under verified stamps) and
                # replaces a torn delta result with a full recompute
                try:
                    table = self.maintainer.finish(inc_ctx, table)
                except BaseException as e:
                    if inc_ctx.mode == "delta":
                        # a delta result whose stamp verification (or
                        # torn-result recompute) failed must never be
                        # streamed as if it were the full answer
                        if conn.alive:
                            self._send_err(conn, infl.tag,
                                           type(e).__name__, str(e))
                        return
                    # capture-mode maintenance is bookkeeping only: the
                    # computed table itself is the plain full result
            elif cacheable:
                # only freeze the result when the sources still carry
                # the pre-execution stamps: a file rewritten mid-query
                # must not cache a half-old result under either stamp
                from spark_rapids_tpu.exec import incremental
                try:
                    post = incremental.current_stamps(plan) \
                        if plan is not None else None
                except Exception:
                    post = None
                if post is not None and post == stamps:
                    result_cache.insert(cache_key, names, stamps,
                                        table)
            # retain the materialized result for resume BEFORE the
            # first chunk goes out: a drain or disconnect at any point
            # of the stream finds the replay source already in place
            _retain_stream(sess.resume_token, stream_id, table=table)
            self._stream_table(conn, infl, table, cache_hit=False,
                               query_id=fut.query_id, release=release,
                               after_seq=fed or 0,
                               observe_first=not fed,
                               feed=fl.chunk_feed if is_leader
                               and fl.had_followers else None)
        finally:
            if is_leader and fl.chunk_feed is not None:
                # error-path net: no-op when the stream finished cleanly
                fl.chunk_feed.abort()
            release()

    def _stream_from_feed(self, conn: _Conn, infl: _Inflight,
                          feed: _ChunkFeed, query_id, release
                          ) -> Tuple[str, int]:
        """Stream a follower's response straight off the leader flight's
        encoded-chunk feed (sends END itself on success).  Returns
        ``('done', n)`` after a complete stream, ``('dead', n)`` when
        this follower's connection/credit is gone, ``('abort', n)`` when
        the LEADER's stream died or stalled — the caller falls back to
        whole-result streaming with ``after_seq=n``."""
        from spark_rapids_tpu.obs import accounting as acct
        reg = obsreg.get_registry()
        sent = 0
        try:
            while True:
                kind, payload = feed.next(sent)
                if kind == "abort":
                    return "abort", sent
                if kind == "done":
                    break
                if not conn.alive or not infl.take_credit():
                    if conn.alive:
                        code = infl.abort_code or "StreamAborted"
                        self._send_err(
                            conn, infl.tag, code,
                            "server draining; reconnect and resume"
                            if code == "Draining"
                            else "stream cancelled or stalled")
                    return "dead", sent
                wire.send_frame(conn.sock, conn.wlock, wire.CHUNK,
                                infl.tag,
                                wire.encode_chunk(sent + 1, payload),
                                stall_s=self._write_stall_s)
                sent += 1
                if sent == 1:
                    acct.observe_slo(
                        "slo.firstChunkMs",
                        (time.monotonic_ns() - infl.t0_ns) / 1e6,
                        template=infl.template)
                reg.inc_many(("serve.streamedBatches", 1),
                             ("serve.dedup.fedChunks", 1))
            if conn.alive and not infl.aborted:
                release()
                wire.send_frame(
                    conn.sock, conn.wlock, wire.END, infl.tag,
                    wire.encode_msg({"rows": feed.rows,
                                     "chunks": sent,
                                     "cache_hit": False,
                                     "query_id": query_id,
                                     "last_seq": feed.total}),
                    stall_s=self._write_stall_s)
                acct.observe_slo(
                    "slo.latencyMs",
                    (time.monotonic_ns() - infl.t0_ns) / 1e6,
                    template=infl.template)
            return "done", sent
        except wire.ServeWireError as e:
            if e.reason == "writeStall":
                reg.inc("serve.wire.writeStalls")
                obsrec.record_event("serve.writeStall",
                                    client=conn.addr, tag=infl.tag)
            infl.abort()
            try:
                conn.sock.close()
            except OSError:
                pass
            return "dead", sent
        except wire.WireError:
            infl.abort()
            try:
                conn.sock.close()
            except OSError:
                pass
            return "dead", sent

    def _stream_table(self, conn: _Conn, infl: _Inflight, table,
                      cache_hit: bool, query_id, release,
                      after_seq: int = 0, observe_first: bool = True,
                      feed: Optional[_ChunkFeed] = None) -> None:
        reg = obsreg.get_registry()
        chunks = wire.table_chunks(table, self._chunk_rows)
        total = max(1, math.ceil(max(1, table.num_rows)
                                 / self._chunk_rows))
        sent = 0
        seq = 0
        try:
            for payload in chunks:
                seq += 1
                if feed is not None:
                    # relay the encoded payload to flight followers
                    # BEFORE this stream's own credit/fault gates: a
                    # stalled leader client must not hold back chunks
                    # already paid for
                    feed.publish(payload)
                if seq <= after_seq:
                    # resume replay: chunks the client already acked
                    # are skipped, never re-sent — duplicate-freedom
                    # is by sequence number, not client-side dedupe
                    continue
                if not conn.alive or not infl.take_credit():
                    if conn.alive:
                        # aborted mid-stream (explicit cancel, drain,
                        # or credit stall) on a live connection:
                        # terminate the client's stream explicitly
                        code = infl.abort_code or "StreamAborted"
                        self._send_err(
                            conn, infl.tag, code,
                            "server draining; reconnect and resume"
                            if code == "Draining"
                            else "stream cancelled or stalled")
                    return
                ev = serve_faults.check("stream.chunk")
                if ev is not None:
                    if ev.action is ServeFaultAction.DROP:
                        # the client sees a sequence hole and resumes
                        continue
                    if ev.action is ServeFaultAction.CLOSE:
                        try:
                            conn.sock.close()
                        except OSError:
                            pass
                        infl.abort()
                        return
                    if ev.action in (ServeFaultAction.DELAY,
                                     ServeFaultAction.SLOW):
                        # SLOW on the server streamer = a degraded
                        # chunk send (the sentinel probe's latency
                        # injection); DELAY keeps its one-shot stall
                        time.sleep(ev.delay_s)
                wire.send_frame(conn.sock, conn.wlock, wire.CHUNK,
                                infl.tag, wire.encode_chunk(seq, payload),
                                stall_s=self._write_stall_s)
                sent += 1
                if sent == 1 and observe_first:
                    from spark_rapids_tpu.obs import accounting as acct
                    acct.observe_slo(
                        "slo.firstChunkMs",
                        (time.monotonic_ns() - infl.t0_ns) / 1e6,
                        template=infl.template)
                reg.inc("serve.streamedBatches")
            if feed is not None:
                feed.finish(table.num_rows, total)
            if conn.alive and not infl.aborted:
                release()
                wire.send_frame(
                    conn.sock, conn.wlock, wire.END, infl.tag,
                    wire.encode_msg({"rows": table.num_rows,
                                     "chunks": sent,
                                     "cache_hit": cache_hit,
                                     "query_id": query_id,
                                     "last_seq": total}),
                    stall_s=self._write_stall_s)
                # serve-side e2e: request receipt -> END frame (the
                # sched layer skips serve-attributed queries, so one
                # observation per request, never two)
                from spark_rapids_tpu.obs import accounting as acct
                acct.observe_slo(
                    "slo.latencyMs",
                    (time.monotonic_ns() - infl.t0_ns) / 1e6,
                    template=infl.template)
        except wire.ServeWireError as e:
            # a write stall is the peer's fault, and the partial frame
            # desynced the stream: typed counter, abort, close
            if e.reason == "writeStall":
                reg.inc("serve.wire.writeStalls")
                obsrec.record_event("serve.writeStall",
                                    client=conn.addr, tag=infl.tag)
            infl.abort()
            try:
                conn.sock.close()
            except OSError:
                pass
        except wire.WireError:
            infl.abort()
