"""The serving front-end: a long-lived TCP server over QueryService.

One ``ServeServer`` per engine session when ``serve.enabled=true``
(api/session.py keeps it on ``session.serve_server``; ``serve.port=0``
binds ephemeral, discover via ``serve_server.port``).  Layering::

    ServeClient ──wire──> ServeServer ──submit(meta)──> QueryService
                             │                             (PR 5)
                             ├─ ServeSession  (conf overlay, fair share,
                             │                 prepared statements,
                             │                 idle eviction)
                             └─ result_cache  (digest+stamp keyed)

Per connection a reader thread owns the socket's inbound side; query
ops submit asynchronously and a per-query streamer thread delivers
CHUNK frames under the client's credit (wire.py) — the reader stays
responsive for CREDIT and cancel frames while results stream.  A dead
socket cancels every in-flight query through PR 5's CancelToken, so an
abandoned query releases its admission slot, drains its prefetcher and
frees its spill-catalog entries exactly like an explicit cancel.

Fair share: at most ``serve.session.maxInFlight`` queries per session
may be in flight; past it the request is refused with a typed
``FairShareExceeded`` error (back-pressure to THAT client) instead of
queueing — one greedy client cannot monopolize ``sched.memoryBudget``.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time
import weakref
from typing import Any, Dict, Optional

from spark_rapids_tpu.obs import recorder as obsrec
from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.serve import result_cache, wire
from spark_rapids_tpu.serve.statements import (PreparedStatement,
                                               StatementError)

# a streamer blocked on client credit longer than this aborts: a
# wedged consumer must not pin its result table and fair-share slot
# forever (idle eviction only covers sessions with nothing in flight)
_STREAM_STALL_S = 300.0


class ServeError(Exception):
    """Typed server-side request failure; ``code`` rides the ERR frame."""

    def __init__(self, code: str, msg: str):
        super().__init__(msg)
        self.code = code


class ServeSession:
    """Server-side client session: id, conf overlay, prepared
    statements, and the fair-share in-flight gate."""

    __slots__ = ("session_id", "priority", "timeout_ms",
                 "estimate_bytes", "max_inflight", "statements",
                 "inflight", "last_active", "created_unix", "closed",
                 "client_addr", "_lock")

    def __init__(self, session_id: str, overlay: Dict[str, Any],
                 max_inflight: int, client_addr: str):
        self.session_id = session_id
        self.priority = int(overlay.get("priority", 0) or 0)
        t = overlay.get("timeoutMs")
        self.timeout_ms = int(t) if t else None
        e = overlay.get("estimateBytes")
        self.estimate_bytes = int(e) if e else None
        self.max_inflight = max(1, int(max_inflight))
        self.statements: Dict[str, PreparedStatement] = {}
        self.inflight = 0
        self.created_unix = time.time()
        self.last_active = time.monotonic()
        self.closed = False
        self.client_addr = client_addr
        self._lock = threading.Lock()

    def touch(self) -> None:
        self.last_active = time.monotonic()

    def try_begin_query(self) -> bool:
        with self._lock:
            if self.closed or self.inflight >= self.max_inflight:
                return False
            self.inflight += 1
            return True

    def end_query(self) -> None:
        with self._lock:
            self.inflight = max(0, self.inflight - 1)
        self.touch()

    def describe(self) -> Dict[str, Any]:
        return {"session_id": self.session_id,
                "priority": self.priority,
                "timeout_ms": self.timeout_ms,
                "estimate_bytes": self.estimate_bytes,
                "max_inflight": self.max_inflight,
                "inflight": self.inflight,
                "statements": sorted(self.statements),
                "client_addr": self.client_addr}


class _Inflight:
    """One query being answered on one connection: its future (None for
    a result-cache hit) and the client-credit window."""

    def __init__(self, tag: int, future, credit: int):
        self.tag = tag
        self.future = future
        self._credit = max(0, int(credit))
        self._cv = threading.Condition()
        self.aborted = False

    def add_credit(self, n: int) -> None:
        with self._cv:
            self._credit += max(0, int(n))
            self._cv.notify_all()

    def abort(self) -> None:
        with self._cv:
            self.aborted = True
            self._cv.notify_all()

    def take_credit(self) -> bool:
        """Block until one CHUNK of credit is available; False when the
        stream aborted (disconnect/cancel) or stalled out."""
        deadline = time.monotonic() + _STREAM_STALL_S
        with self._cv:
            while True:
                if self.aborted:
                    return False
                if self._credit > 0:
                    self._credit -= 1
                    return True
                if time.monotonic() >= deadline:
                    self.aborted = True
                    return False
                self._cv.wait(timeout=0.25)


class _Conn:
    __slots__ = ("sock", "wlock", "addr", "alive", "session",
                 "inflight", "closed_cleanly", "_lock")

    def __init__(self, sock: socket.socket, addr: str):
        self.sock = sock
        self.wlock = threading.Lock()
        self.addr = addr
        self.alive = True
        self.session: Optional[ServeSession] = None
        self.inflight: Dict[int, _Inflight] = {}
        self.closed_cleanly = False
        self._lock = threading.Lock()

    def track(self, infl: _Inflight) -> None:
        with self._lock:
            self.inflight[infl.tag] = infl

    def untrack(self, tag: int) -> None:
        with self._lock:
            self.inflight.pop(tag, None)

    def take_all(self) -> list:
        with self._lock:
            out = list(self.inflight.values())
            self.inflight.clear()
        return out


class ServeServer:
    """See module docstring.  One per engine session; ``shutdown()`` is
    idempotent and also fires when the engine session is collected."""

    def __init__(self, session):
        import hashlib

        from spark_rapids_tpu import config as cfg
        conf = session.conf
        self._engine_ref = weakref.ref(session)
        # semantics stamp: the engine session's result-affecting SQL
        # configuration participates in every result-cache key, so a
        # later session in the same process with different semantics
        # knobs (float-agg ordering, incompat ops, cast behavior…) can
        # never be served a result this session computed — the cache
        # itself is process-global.  Over-invalidation (a knob that
        # doesn't really change results) only costs a miss.
        sql_conf = sorted((k, repr(v)) for k, v in
                          conf._settings.items()
                          if k.startswith("spark.rapids.tpu.sql"))
        self._semantics_stamp = hashlib.sha1(
            repr(sql_conf).encode()).hexdigest()[:16]
        self._max_inflight = int(conf.get(cfg.SERVE_SESSION_MAX_INFLIGHT))
        self._idle_timeout_s = max(
            0.05, int(conf.get(cfg.SERVE_SESSION_IDLE_TIMEOUT_MS)) / 1e3)
        self._chunk_rows = max(
            1, int(conf.get(cfg.SERVE_STREAM_CHUNK_ROWS)))
        result_cache.configure(
            bool(conf.get(cfg.SERVE_RESULT_CACHE_ENABLED)),
            int(conf.get(cfg.SERVE_RESULT_CACHE_MAX_BYTES)))
        # incremental result maintenance (exec/incremental.py): delta
        # scans + retained aggregate partials over the result cache,
        # plus the background stamp-polling refresher
        from spark_rapids_tpu.exec.incremental import \
            IncrementalMaintainer
        self.maintainer = IncrementalMaintainer(session)
        self._sessions: Dict[str, ServeSession] = {}
        self._lock = threading.Lock()
        self._session_seq = itertools.count(1)
        self._stmt_seq = itertools.count(1)
        self._stop = threading.Event()
        host = str(conf.get(cfg.SERVE_HOST))
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, int(conf.get(cfg.SERVE_PORT))))
        self._lsock.listen(128)
        self.host = host
        self.port = self._lsock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"serve-accept-{self.port}",
            daemon=True)
        self._accept_thread.start()
        self._janitor = threading.Thread(
            target=self._janitor_loop, name=f"serve-janitor-{self.port}",
            daemon=True)
        self._janitor.start()
        self._finalizer = weakref.finalize(
            session, ServeServer._static_shutdown, self._lsock,
            self._stop)

    # -- lifecycle ---------------------------------------------------------
    @staticmethod
    def _static_shutdown(lsock, stop) -> None:
        stop.set()
        try:
            lsock.close()
        except OSError:
            pass

    def shutdown(self) -> None:
        self._static_shutdown(self._lsock, self._stop)
        self.maintainer.shutdown()
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for s in sessions:
            s.closed = True
        # release the materialized results: the cache is process-global
        # and would otherwise pin up to its whole byte budget of
        # pa.Tables after the serving session is gone (the semantics
        # stamp already guarantees a later session can't be served
        # stale semantics; this is purely about memory)
        result_cache.clear()
        obsreg.get_registry().set_gauge("serve.activeSessions", 0)

    def _engine(self):
        eng = self._engine_ref()
        if eng is None:
            raise ServeError("ServerStopping",
                             "engine session gone; server stopping")
        return eng

    # -- session registry --------------------------------------------------
    def sessions(self) -> Dict[str, ServeSession]:
        with self._lock:
            return dict(self._sessions)

    def _publish_sessions(self) -> None:
        obsreg.get_registry().set_gauge("serve.activeSessions",
                                        len(self._sessions))

    def _open_session(self, overlay: Dict[str, Any],
                      addr: str) -> ServeSession:
        sid = f"s-{next(self._session_seq):05d}"
        sess = ServeSession(sid, overlay or {}, self._max_inflight, addr)
        with self._lock:
            self._sessions[sid] = sess
            self._publish_sessions()
        reg = obsreg.get_registry()
        reg.inc("serve.sessions")
        obsrec.record_event("serve.sessionOpened", session=sid,
                            client_addr=addr)
        return sess

    def _evict(self, sess: ServeSession, reason: str) -> None:
        with self._lock:
            cur = self._sessions.get(sess.session_id)
            if cur is not sess:
                return
            del self._sessions[sess.session_id]
            self._publish_sessions()
        sess.closed = True
        obsreg.get_registry().inc("serve.sessionsEvicted")
        obsrec.record_event("serve.sessionEvicted",
                            session=sess.session_id, reason=reason)

    def _janitor_loop(self) -> None:
        interval = min(2.0, max(0.02, self._idle_timeout_s / 4))
        while not self._stop.wait(interval):
            now = time.monotonic()
            for sess in list(self.sessions().values()):
                if sess.inflight == 0 and \
                        now - sess.last_active > self._idle_timeout_s:
                    self._evict(sess, "idle-timeout")

    # -- accept / per-connection reader ------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, addr = self._lsock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn,
                args=(sock, f"{addr[0]}:{addr[1]}"),
                name=f"serve-conn-{addr[1]}", daemon=True).start()

    def _serve_conn(self, sock: socket.socket, addr: str) -> None:
        conn = _Conn(sock, addr)
        try:
            while not self._stop.is_set():
                frame = wire.read_frame(sock)
                if frame is None:
                    return
                kind, tag, payload = frame
                if kind == wire.CREDIT:
                    msg = wire.decode_msg(payload)
                    infl = conn.inflight.get(tag)
                    if infl is not None:
                        infl.add_credit(int(msg.get("n", 1)))
                elif kind == wire.REQ:
                    if not self._handle_request(
                            conn, tag, wire.decode_msg(payload)):
                        return
                # other kinds from a client are protocol noise: ignore
        except wire.WireError:
            pass
        finally:
            self._on_disconnect(conn)
            try:
                sock.close()
            except OSError:
                pass

    def _on_disconnect(self, conn: _Conn) -> None:
        conn.alive = False
        pending = conn.take_all()
        for infl in pending:
            infl.abort()
            if infl.future is not None:
                infl.future.cancel("client disconnected")
        if conn.session is not None:
            conn.session.touch()
        if not conn.closed_cleanly:
            obsreg.get_registry().inc("serve.clientDisconnects")
            if pending:
                obsrec.record_event(
                    "serve.disconnectCancelled",
                    session=getattr(conn.session, "session_id", None),
                    cancelled=len(pending))

    # -- request dispatch --------------------------------------------------
    def _send_resp(self, conn: _Conn, tag: int,
                   obj: Dict[str, Any]) -> None:
        wire.send_frame(conn.sock, conn.wlock, wire.RESP, tag,
                        wire.encode_msg(obj))

    def _send_err(self, conn: _Conn, tag: int, code: str,
                  msg: str) -> None:
        try:
            wire.send_frame(conn.sock, conn.wlock, wire.ERR, tag,
                            wire.encode_msg({"type": code,
                                             "error": msg}))
        except wire.WireError:
            pass

    def _handle_request(self, conn: _Conn, tag: int,
                        msg: Dict[str, Any]) -> bool:
        """Dispatch one REQ; returns False when the connection should
        close (the ``close`` op)."""
        op = str(msg.get("op", ""))
        reg = obsreg.get_registry()
        reg.inc("serve.requests")
        try:
            if op == "hello":
                sess = self._open_session(msg.get("conf") or {},
                                          conn.addr)
                conn.session = sess
                self._send_resp(conn, tag, {
                    "session_id": sess.session_id,
                    "protocol": wire.PROTOCOL_VERSION,
                    "engine": "spark-rapids-tpu"})
                return True
            if op == "ping":
                self._send_resp(conn, tag, {"ok": True})
                return True
            if op == "close":
                conn.closed_cleanly = True
                if conn.session is not None and \
                        bool(msg.get("end_session", True)):
                    self._evict(conn.session, "client-close")
                self._send_resp(conn, tag, {"ok": True})
                return False
            sess = self._session_of(conn)
            sess.touch()
            if op == "sql":
                plan = self._parse(str(msg.get("sql", "")))
                self._start_query(conn, tag, sess, plan,
                                  int(msg.get("credit", 8)))
            elif op == "prepare":
                stmt = self._prepare(sess, msg)
                self._send_resp(conn, tag, stmt.describe())
            elif op == "execute":
                stmt = self._statement_of(sess, msg)
                plan = stmt.bind(msg.get("params") or {})
                self._start_query(conn, tag, sess, plan,
                                  int(msg.get("credit", 8)))
            elif op == "close_statement":
                sid = str(msg.get("statement_id", ""))
                sess.statements.pop(sid, None)
                self._send_resp(conn, tag, {"ok": True})
            elif op == "cancel":
                target = int(msg.get("request", -1))
                infl = conn.inflight.get(target)
                cancelled = False
                if infl is not None:
                    infl.abort()
                    if infl.future is not None:
                        cancelled = infl.future.cancel(
                            "cancelled by client")
                self._send_resp(conn, tag, {"cancelled": cancelled})
            elif op == "session_info":
                self._send_resp(conn, tag, sess.describe())
            else:
                raise ServeError("UnknownOp",
                                 f"unknown request op {op!r}")
        except ServeError as e:
            self._send_err(conn, tag, e.code, str(e))
        except StatementError as e:
            self._send_err(conn, tag, "StatementError", str(e))
        except wire.WireError:
            raise
        except Exception as e:
            self._send_err(conn, tag, type(e).__name__, str(e))
        return True

    def _session_of(self, conn: _Conn) -> ServeSession:
        sess = conn.session
        if sess is None:
            raise ServeError("NoSession",
                             "send a hello request before queries")
        if sess.closed or sess.session_id not in self.sessions():
            raise ServeError(
                "SessionExpired",
                f"session {sess.session_id} was evicted "
                f"(idle > {self._idle_timeout_s:.1f}s or closed); "
                f"send a new hello")
        return sess

    def _statement_of(self, sess: ServeSession,
                      msg: Dict[str, Any]) -> PreparedStatement:
        sid = str(msg.get("statement_id", ""))
        stmt = sess.statements.get(sid)
        if stmt is None:
            raise ServeError("UnknownStatement",
                             f"no prepared statement {sid!r} in "
                             f"session {sess.session_id}")
        return stmt

    def _parse(self, sql: str):
        if not sql.strip():
            raise ServeError("EmptyStatement", "empty sql")
        from spark_rapids_tpu.sql import parse_sql
        return parse_sql(sql, self._engine().catalog)

    def _prepare(self, sess: ServeSession,
                 msg: Dict[str, Any]) -> PreparedStatement:
        sql = str(msg.get("sql", ""))
        if not sql.strip():
            raise ServeError("EmptyStatement", "empty sql")
        stmt_id = f"stmt-{next(self._stmt_seq):05d}"
        stmt = PreparedStatement(stmt_id, sql, msg.get("params") or {},
                                 self._engine().catalog)
        sess.statements[stmt_id] = stmt
        obsreg.get_registry().inc("serve.statementsPrepared")
        return stmt

    # -- query execution + streaming ---------------------------------------
    def _start_query(self, conn: _Conn, tag: int, sess: ServeSession,
                     plan, credit: int) -> None:
        if not sess.try_begin_query():
            raise ServeError(
                "FairShareExceeded",
                f"session {sess.session_id} already has "
                f"{sess.max_inflight} queries in flight "
                f"(serve.session.maxInFlight)")
        try:
            digest = cache_key = names = stamps = None
            cacheable = False
            submit_plan, inc_ctx = plan, None
            try:
                from spark_rapids_tpu.exec import incremental
                from spark_rapids_tpu.plan.digest import plan_fingerprint
                fp = plan_fingerprint(plan)
                digest = fp.digest
                # cache entries key on (semantics stamp, plan digest):
                # the profile//queries surface the pure digest, the
                # cache must also see the session's SQL conf
                cache_key = f"{self._semantics_stamp}:{fp.digest}"
                names = tuple(plan.schema.names)
                if fp.cacheable and result_cache.enabled():
                    # stamps come from the LIVE expansion of the scan's
                    # source roots (not the frozen read()-time file
                    # list) so a file appended to a watched dataset
                    # invalidates — and delta-refreshes — the entry
                    stamps = incremental.current_stamps(plan)
                    cacheable = stamps is not None
            except Exception:
                cacheable = False
            if cacheable:
                hit = result_cache.lookup(cache_key, names, stamps)
                if hit is not None:
                    infl = _Inflight(tag, None, credit)
                    conn.track(infl)
                    threading.Thread(
                        target=self._stream_cached,
                        args=(conn, sess, infl, hit),
                        name=f"serve-stream-{tag}", daemon=True).start()
                    return
                # incremental maintenance decides full-capture vs delta
                # (and re-pins watched scans to the live file set so
                # the executed plan reads what the stamps describe)
                submit_plan, inc_ctx = self.maintainer.prepare(
                    plan, cache_key, names, stamps)
            eng = self._engine()
            meta = {"session_id": sess.session_id,
                    "client_addr": sess.client_addr}
            if digest is not None:
                meta["plan_digest"] = digest  # already computed here
            fut = eng.scheduler.submit(
                submit_plan, priority=sess.priority,
                timeout_ms=sess.timeout_ms,
                estimate_bytes=sess.estimate_bytes,
                meta=meta)
            infl = _Inflight(tag, fut, credit)
            conn.track(infl)
            threading.Thread(
                target=self._stream_result,
                args=(conn, sess, infl, cache_key, names, stamps,
                      cacheable, plan, inc_ctx),
                name=f"serve-stream-{tag}", daemon=True).start()
        except BaseException:
            sess.end_query()
            raise

    @staticmethod
    def _releaser(conn: _Conn, sess: ServeSession, infl: _Inflight):
        """Once-only release of the query's fair-share slot + in-flight
        tracking.  Called just BEFORE the END frame goes out (so a
        client that pipelines its next query the instant END arrives
        can never race a still-held slot into FairShareExceeded) and
        again from the streamer's finally as the error-path net."""
        done = threading.Event()

        def release() -> None:
            if not done.is_set():
                done.set()
                conn.untrack(infl.tag)
                sess.end_query()
        return release

    def _stream_cached(self, conn: _Conn, sess: ServeSession,
                       infl: _Inflight, table) -> None:
        release = self._releaser(conn, sess, infl)
        try:
            self._stream_table(conn, infl, table, cache_hit=True,
                               query_id=None, release=release)
        finally:
            release()

    def _stream_result(self, conn: _Conn, sess: ServeSession,
                       infl: _Inflight, cache_key, names, stamps,
                       cacheable: bool, plan=None, inc_ctx=None) -> None:
        fut = infl.future
        release = self._releaser(conn, sess, infl)
        try:
            try:
                table = fut.result()
            except BaseException as e:
                # a live connection always gets a terminal frame (an
                # explicitly cancelled stream included — only a dead
                # socket goes unanswered), or the client would wait on
                # a stream that will never end
                if conn.alive:
                    self._send_err(conn, infl.tag, type(e).__name__,
                                   str(e))
                return
            if inc_ctx is not None:
                # the maintainer owns caching for maintained runs
                # (result + partial state under verified stamps) and
                # replaces a torn delta result with a full recompute
                try:
                    table = self.maintainer.finish(inc_ctx, table)
                except BaseException as e:
                    if inc_ctx.mode == "delta":
                        # a delta result whose stamp verification (or
                        # torn-result recompute) failed must never be
                        # streamed as if it were the full answer
                        if conn.alive:
                            self._send_err(conn, infl.tag,
                                           type(e).__name__, str(e))
                        return
                    # capture-mode maintenance is bookkeeping only: the
                    # computed table itself is the plain full result
            elif cacheable:
                # only freeze the result when the sources still carry
                # the pre-execution stamps: a file rewritten mid-query
                # must not cache a half-old result under either stamp
                from spark_rapids_tpu.exec import incremental
                try:
                    post = incremental.current_stamps(plan) \
                        if plan is not None else None
                except Exception:
                    post = None
                if post is not None and post == stamps:
                    result_cache.insert(cache_key, names, stamps,
                                        table)
            self._stream_table(conn, infl, table, cache_hit=False,
                               query_id=fut.query_id, release=release)
        finally:
            release()

    def _stream_table(self, conn: _Conn, infl: _Inflight, table,
                      cache_hit: bool, query_id, release) -> None:
        reg = obsreg.get_registry()
        chunks = wire.table_chunks(table, self._chunk_rows)
        sent = 0
        try:
            for payload in chunks:
                if not conn.alive or not infl.take_credit():
                    if conn.alive:
                        # aborted mid-stream (explicit cancel or credit
                        # stall) on a live connection: terminate the
                        # client's stream explicitly
                        self._send_err(conn, infl.tag, "StreamAborted",
                                       "stream cancelled or stalled")
                    return
                wire.send_frame(conn.sock, conn.wlock, wire.CHUNK,
                                infl.tag, payload)
                sent += 1
                reg.inc("serve.streamedBatches")
            if conn.alive and not infl.aborted:
                release()
                wire.send_frame(
                    conn.sock, conn.wlock, wire.END, infl.tag,
                    wire.encode_msg({"rows": table.num_rows,
                                     "chunks": sent,
                                     "cache_hit": cache_hit,
                                     "query_id": query_id}))
        except wire.WireError:
            infl.abort()
