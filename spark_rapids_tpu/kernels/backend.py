"""Kernel-backend selection: hand-written Pallas kernels vs composed XLA.

The engine's hot decode/aggregate paths are gather-bandwidth-bound at
the XLA level (PERF.md round-4b cost model: i64 gathers 22 ms/M values,
64-bit scatters ~14x i32) and XLA-level reformulations are exhausted
(ROADMAP open item 2).  This package holds purpose-built Pallas kernels
for exactly those shapes — Eiger's purpose-built-analytics-primitives
argument (arXiv:2607.04489) applied to the three measured walls:

  * ``decode.unpack`` / ``decode.expand`` — dense phase-decomposed
    RLE/bit-unpack for Parquet streams (kernels/decode.py)
  * ``scan.filterDecode`` — fused dictionary-decode + filter that never
    materializes decoded values for filtered-out rows
    (kernels/filter_decode.py)
  * ``agg.segreduce`` — single-pass segmented reduction for the
    sorted-key grouped aggregate (kernels/segreduce.py)

Selection contract (the ``sql.fusion.enabled`` pattern end to end):

  * ``spark.rapids.tpu.kernel.backend`` picks ``xla`` (default, the
    existing composed-array-op paths) or ``pallas``.
  * The choice is PER CALL SITE with per-kernel fallback: a shape or
    dtype a Pallas kernel doesn't cover silently takes the XLA path for
    THAT kernel only — never the whole query (GPU-join-on-Hadoop,
    arXiv:1904.11201: fallback cliffs dominate when the fast path isn't
    universally applicable and degradation is coarse-grained).
  * Every selection is observable: ``kernel.backend.pallas.hits`` and
    ``kernel.backend.pallas.fallbacks`` (plus reason- and family-tagged
    variants ``...fallbacks.<family>.<reason>``) in the metrics
    registry, and per-dispatch attribution via the
    ``kernel.dispatches.<family>.<backend>`` counters
    (exec/kernel_cache.py).

Counting semantics: hits/fallbacks are SELECTION events.  Host-side
call sites (per-column stream expansion, scan prepare) select once per
call, so those counters track per-batch work; selections made while
TRACING a cached kernel (the aggregate's segmented reductions) count
once per compile — the per-dispatch ground truth is always
``kernel.dispatches.<family>.<backend>``.

Interpret mode: Pallas kernels run under ``interpret=True`` whenever
the active jax backend is not a real TPU (``kernel.pallas.interpret``
= auto), so CPU CI (`JAX_PLATFORMS=cpu`) executes the REAL kernel
bodies and the parity gates exercise actual kernel semantics, not a
skip.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

XLA = "xla"
PALLAS = "pallas"

_lock = threading.Lock()
_default_backend = PALLAS
_interpret_mode = "auto"        # auto | true | false
_tile_bytes = 4 << 20           # kernel.pallas.tileBytes default
_pallas_available: Optional[bool] = None
# memoized resolution of interpret='auto' (the active-jax-backend
# probe): jax.default_backend() is a per-dispatch cost the tile-plan /
# kernel-selection hot path must not pay, and the platform cannot
# change mid-process.  Pinned modes ('true'/'false') bypass the memo.
_auto_interpret: Optional[bool] = None


def configure(conf) -> None:
    """Session-init hook: install the process default backend from
    ``spark.rapids.tpu.kernel.backend`` (the scan-cache ``configure``
    idiom — every new session re-asserts its own conf, so a prior
    session's setting never leaks into an unconfigured one).  Plans
    additionally carry a per-plan ``_kernel_backend`` stamp
    (plan/overrides.py), which wins over this default wherever a plan
    node is in scope."""
    from spark_rapids_tpu import config as cfg
    global _default_backend, _interpret_mode, _tile_bytes
    backend = str(conf.get(cfg.KERNEL_BACKEND) or PALLAS).strip().lower()
    if backend not in (XLA, PALLAS):
        raise ValueError(
            f"spark.rapids.tpu.kernel.backend must be 'xla' or "
            f"'pallas', got {backend!r}")
    mode = str(conf.get(cfg.KERNEL_PALLAS_INTERPRET)
               or "auto").strip().lower()
    tb_raw = conf.get(cfg.KERNEL_PALLAS_TILE_BYTES)
    tb = int(tb_raw) if tb_raw is not None else (4 << 20)
    if tb < (64 << 10):
        raise ValueError(
            f"spark.rapids.tpu.kernel.pallas.tileBytes must be at "
            f"least 64 KiB, got {tb}")
    with _lock:
        _default_backend = backend
        _interpret_mode = mode
        _tile_bytes = tb


def default_backend() -> str:
    with _lock:
        return _default_backend


def set_default_backend(backend: str) -> None:
    """Test/bench hook (sessions should go through :func:`configure`)."""
    global _default_backend
    with _lock:
        _default_backend = backend


@contextmanager
def backend_override(backend: str):
    """Scoped default-backend override for benches and tests."""
    prev = default_backend()
    set_default_backend(backend)
    try:
        yield
    finally:
        set_default_backend(prev)


def resolve(stamped: Optional[str] = None) -> str:
    """The backend in effect at a call site: the plan-stamped value
    when the caller has one (``_kernel_backend``), else the process
    default."""
    if stamped in (XLA, PALLAS):
        return stamped
    return default_backend()


def pallas_available() -> bool:
    """Import probe, memoized: environments without the Pallas
    extension degrade to XLA everywhere (counted as fallbacks with
    reason ``unavailable``)."""
    global _pallas_available
    if _pallas_available is None:
        try:
            from jax.experimental import pallas  # noqa: F401
            from jax.experimental.pallas import tpu  # noqa: F401
            _pallas_available = True
        except Exception:
            _pallas_available = False
    return _pallas_available


def interpret() -> bool:
    """Run Pallas kernels in interpreter mode?  ``auto`` (default):
    interpret unless the active jax backend is a real TPU — so tier-1
    CPU runs execute the genuine kernel bodies.  The knob pins it for
    debugging (``true``) or to force Mosaic compilation (``false``).

    The ``auto`` probe (``jax.default_backend()``) is memoized: it used
    to re-resolve on every dispatch/tile-plan lookup, but the active
    platform cannot change mid-process — only the pinned modes bypass
    the memo (they are a plain mode-string compare anyway)."""
    global _auto_interpret
    with _lock:
        mode = _interpret_mode
    if mode in ("true", "1", "yes", "on"):
        return True
    if mode in ("false", "0", "no", "off"):
        return False
    if _auto_interpret is None:
        try:
            import jax
            _auto_interpret = jax.default_backend() != "tpu"
        except Exception:
            _auto_interpret = True
    return _auto_interpret


def tile_bytes() -> int:
    """Per-tile byte budget of the HBM->VMEM streaming tiler
    (``kernel.pallas.tileBytes``) — the knob kernels/tiling.py plans
    grids against.  Part of every tiled kernel's cache key (via the
    tile plan's block/tile shapes), so flipping it mid-process can
    never serve a stale grid."""
    with _lock:
        return _tile_bytes


@contextmanager
def tile_bytes_override(n: int):
    """Scoped tileBytes override for benches and tile-boundary tests
    (forcing multi-tile grids on small buffers)."""
    global _tile_bytes
    with _lock:
        prev = _tile_bytes
        _tile_bytes = int(n)
    try:
        yield
    finally:
        with _lock:
            _tile_bytes = prev


def hit(family: str, n: int = 1) -> None:
    """Record a Pallas selection (see the counting-semantics note in
    the module docstring)."""
    from spark_rapids_tpu.obs import registry as obsreg
    obsreg.get_registry().inc_many(
        ("kernel.backend.pallas.hits", n),
        (f"kernel.backend.pallas.hits.{family}", n))


def fallback(family: str, reason: str, n: int = 1) -> None:
    """Record a pallas->xla per-kernel fallback with its reason tag."""
    from spark_rapids_tpu.obs import registry as obsreg
    obsreg.get_registry().inc_many(
        ("kernel.backend.pallas.fallbacks", n),
        (f"kernel.backend.pallas.fallbacks.{family}.{reason}", n))


def record_tiles(family: str, n_tiles: int, tile_nbytes: int) -> None:
    """Count one tiled-kernel selection's streaming volume: how many
    HBM->VMEM source tiles the grid walks and how many bytes they
    cover.  These counters replaced the retired whole-buffer residency
    fallbacks (``dense_too_large``/``dict_too_large``/``src_too_large``
    reasons): a buffer past the old gates now shows up as a large tile
    count instead of an XLA fallback.  Same counting semantics as
    :func:`hit` — host call sites count per batch, trace-time call
    sites once per compile."""
    from spark_rapids_tpu.obs import registry as obsreg
    obsreg.get_registry().inc_many(
        ("kernel.pallas.tiles", n_tiles),
        (f"kernel.pallas.tiles.{family}", n_tiles),
        ("kernel.pallas.tileBytes", n_tiles * tile_nbytes),
        (f"kernel.pallas.tileBytes.{family}", n_tiles * tile_nbytes))


def selection_snapshot() -> dict:
    """The ``kernel.backend.*`` selection counters carved from the
    registry as plain ints — the ``/compiles`` endpoint's selection
    block, so compile-bill readers see WHICH backend's programs they
    are looking at (a pallas-requested family that silently fell back
    everywhere compiles XLA programs) next to the churn report."""
    from spark_rapids_tpu.obs import registry as obsreg
    counters = obsreg.get_registry().snapshot()["counters"]
    return {k: int(v) for k, v in sorted(counters.items())
            if k.startswith("kernel.backend.")}


def choose(family: str, backend: str, supported: bool,
           reason: str = "unsupported") -> str:
    """Resolve one call site's backend: ``pallas`` only when requested
    AND available AND the kernel covers this shape/dtype; anything else
    is an observable per-kernel fallback to ``xla``."""
    if backend != PALLAS:
        return XLA
    if not pallas_available():
        fallback(family, "unavailable")
        return XLA
    if not supported:
        fallback(family, reason)
        return XLA
    hit(family)
    return PALLAS
