"""HBM->VMEM streaming tile planner for the Pallas kernel tier.

PR 9's kernels gated whole-buffer VMEM residency (64 MiB dense values,
16 MiB dictionaries, 64 MiB reduction sources) and fell back to XLA
past the gates — exactly the large, memory-bound batches where the
kernels matter most.  This module plans the replacement: every
gather-source buffer (dense decoded values, dictionaries, segmented-
reduction sources) streams through the kernels as a SECOND grid
dimension of fixed-size tiles.  The Pallas pipeline emitter double-
buffers grid-mapped BlockSpec inputs automatically (fetch tile j+1
while tile j computes — the standard HBM->VMEM overlap pattern), so a
2D grid over (element blocks x source tiles) with the source tile
keyed on the inner grid index IS the double-buffered streaming loop.

Plan shape, shared by all three kernel families:

  grid = (n_blocks, n_tiles)           # j (tiles) iterates fastest
  source:  BlockSpec((tile,),  lambda i, j: (j,))
  indices: BlockSpec((block,), lambda i, j: (i,))
  output:  BlockSpec((block,), lambda i, j: (i,))   # revisited over j

The output block's index map ignores ``j``, so the block stays VMEM-
resident across the whole tile sweep and is written back once —
kernels initialize it at ``j == 0`` and accumulate per-tile gathers
under ``pl.when(jnp.any(in_tile))``, which skips the gather (and on
hardware the tile's compute, the DMA still pipelines) for tiles no
element of the block references.  Ragged final tiles are handled by
padding the source to ``n_tiles * tile`` (a dense device-side pad) and
masking in-kernel — a clipped index can land in the pad region only on
lanes the ``in_tile`` predicate already excludes.

Element-block sizes grow with capacity (pow2, bounded by _BLOCK_MAX)
so huge caps don't degenerate into tens of thousands of grid cells —
bounded VMEM per block, bounded grid, and a pure function of the
capacity so it adds no program churn beyond what the capacity tier
already keys.  The one exception is segreduce's blocked float path,
which pins block = 2^15 for bit-parity with exec/scans.seg_scan and
passes it here explicitly.

Plans are memoized in the kernel cache (``kernel_cache.tile_plan``,
``kernel.tilePlan.hits/misses``): a plan is a pure function of the
key below, and the hot dispatch path re-reads it instead of re-walking
the ladders and the config lock.  Block and tile shapes join every
tiled kernel's cache key — they are derived from tier-bucketed buffer
lengths plus the process-wide ``kernel.pallas.tileBytes``, so the keys
stay as coarse as the PR 12 ABI tiers made the shapes themselves.
"""

from __future__ import annotations

from dataclasses import dataclass

from spark_rapids_tpu.kernels import backend as kb

# element-block ceiling: 2^17 u32 lanes = 512 KiB VMEM — small next to
# a default 4 MiB source tile, large enough that a 16M-row cap is a
# 128-cell grid dimension, not 2048
_BLOCK_MAX = 1 << 17
# grid-dimension target: grow the element block (pow2) until the block
# count drops to about this many cells
_BLOCKS_TARGET = 128


def _pow2_ceil(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


@dataclass(frozen=True)
class TilePlan:
    """One tiled kernel's static grid geometry."""
    block: int          # elements per element-block (grid dim 0)
    n_blocks: int
    tile: int           # source elements per HBM->VMEM tile (grid dim 1)
    n_tiles: int
    src_pad: int        # padded source length (= tile * n_tiles)
    tile_nbytes: int    # tile * itemsize

    @property
    def grid(self):
        return (self.n_blocks, self.n_tiles)


def _build(cap: int, block: int, block_max: int, src_len: int,
           itemsize: int, tile_bytes: int) -> TilePlan:
    # element block: the caller's base block, grown (pow2) toward the
    # grid target, capped by block_max and the capacity itself; a
    # non-pow2 cap keeps the base block (the caller's shape gate
    # requires cap % block == 0 either way)
    b = min(_pow2_ceil(cap), block_max,
            max(block, _pow2_ceil(max(cap // _BLOCKS_TARGET, 1))))
    if cap % b:
        b = min(cap, block)
    n_blocks = max(-(-cap // b), 1)
    # source tile: largest pow2 element count under the byte budget; a
    # source that fits one tile whole degenerates to the PR 9
    # single-resident shape (n_tiles == 1)
    t_budget = max(tile_bytes // max(itemsize, 1), 8)
    t = max(min(_pow2_ceil(max(src_len, 1)),
                1 << (t_budget.bit_length() - 1)), 8)
    n_tiles = max(-(-max(src_len, 1) // t), 1)
    return TilePlan(block=b, n_blocks=n_blocks, tile=t, n_tiles=n_tiles,
                    src_pad=t * n_tiles, tile_nbytes=t * itemsize)


def plan(family: str, cap: int, src_len: int, itemsize: int,
         block: int, block_max: int = _BLOCK_MAX,
         tile_bytes: "int | None" = None) -> TilePlan:
    """Memoized tile plan for one (family, shape) call site.

    ``cap``: element capacity (grid dim 0 extent * block).  ``src_len``
    / ``itemsize``: the gather-source buffer being streamed.  ``block``:
    the family's base element-block; pass ``block_max=block`` to pin it
    (segreduce's float-parity 2^15 blocks).  ``tile_bytes`` pins the
    budget for call sites whose eligibility gate already read it (the
    fused-scan plan stamps its assemble-time value so a concurrent
    session reconfiguring the knob between assemble and first trace
    cannot produce a kernel that disagrees with its gate or its cache
    key); None reads the process knob."""
    from spark_rapids_tpu.exec import kernel_cache as kc
    tb = int(tile_bytes) if tile_bytes is not None else kb.tile_bytes()
    key = ("tile_plan", family, int(cap), int(src_len), int(itemsize),
           int(block), int(block_max), tb)
    return kc.tile_plan(
        key, lambda: _build(int(cap), int(block), int(block_max),
                            int(src_len), int(itemsize), tb))
