"""Hand-written Pallas kernels behind the ``kernel.backend`` knob.

See :mod:`spark_rapids_tpu.kernels.backend` for the selection contract
(per-call-site choice, per-kernel fallback, hit/fallback counters) and
docs/kernels.md for the kernel inventory and fallback matrix.
"""

from spark_rapids_tpu.kernels import backend  # noqa: F401
from spark_rapids_tpu.kernels.backend import (PALLAS, XLA,  # noqa: F401
                                              backend_override, choose,
                                              default_backend, resolve)
