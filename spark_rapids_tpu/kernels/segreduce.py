"""Kernel 3: single-pass segmented reduction (Pallas).

The sorted-key grouped aggregate (exec/tpu_aggregate.py) computes every
reduction as a composed chain over [cap]-sized intermediates: gather
values into sorted order (``take_sorted`` — one full materialized
copy), run a cumsum or blocked segmented scan over that copy (a second
full traversal writing a third array), then gather group ends.  On the
gather-bound chip that chain IS the measured ~82 ms q6 aggregate wall
(PERF.md round-5 stage differencing).

This kernel fuses the first two stages into ONE sequential pass:
per block, the sorted-order gather feeds the in-block segmented
``associative_scan`` directly (values never round-trip through HBM as
a sorted copy), and a (flag, value) carry in SMEM scratch threads the
running segment prefix across blocks.  The block size and combine
structure mirror ``exec/scans.seg_scan`` EXACTLY (one
``associative_scan`` per 2^15-element block, elementwise carry
combine; a single full-array scan below that size or for narrow
dtypes) so float results are bit-identical to the XLA path — float
addition is the one order-sensitive op, and an identical reduction
tree is the parity contract CI enforces.

Per-kernel fallback (``kernel.backend.pallas.fallbacks.agg.segreduce.*``):
2-D payloads (string byte matrices), unknown ops, and capacities off
the block grid take the existing XLA formulation for that reduction
only.  Selection happens while TRACING the cached aggregate kernel, so
hits/fallbacks count once per compile; per-dispatch attribution is the
``kernel.dispatches.agg_*.{pallas|xla}`` counters.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu.kernels import backend as kb

_BLOCK = 1 << 15          # MUST match exec/scans._BLOCK (float parity)
# source-array residency gate (bytes): the gather path block-loads the
# full [cap] value array (and the sorted path its block) — past this,
# fall back rather than hand Mosaic an over-VMEM allocation with no
# recovery (the same pending-tiling gate as decode/_DENSE_MAX_BYTES)
_SRC_MAX_BYTES = 64 << 20

_OPS = {"add": jnp.add, "min": jnp.minimum, "max": jnp.maximum}


def op_name(op) -> Optional[str]:
    if op is jnp.add:
        return "add"
    if op is jnp.minimum:
        return "min"
    if op is jnp.maximum:
        return "max"
    return None


def supported(cap: int, dtype, op: Optional[str], ndim: int = 1
              ) -> Tuple[bool, str]:
    if op is None:
        return False, "op"
    if ndim != 1:
        return False, "ndim"
    if np.dtype(dtype).kind not in "iufb":
        return False, "dtype"
    if not (cap <= _BLOCK or cap % _BLOCK == 0):
        return False, "shape"
    if cap * np.dtype(dtype).itemsize > _SRC_MAX_BYTES:
        return False, "src_too_large"
    return True, ""


def _combine(op):
    def combine(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, op(va, vb))
    return combine


def _seg_kernel(op, B: int, blocked: bool, gather: bool, scan_np):
    """Kernel body: [optional sorted-order gather ->] in-block
    segmented scan [-> carry across blocks].  Blocked kernels take the
    op identity as a (1,)-shaped INPUT (last in_ref): it may be a
    traced value (e.g. the string-min word sentinel built under jit),
    which a closure constant could not carry."""
    from jax.experimental import pallas as pl
    combine = _combine(op)

    def kernel(*refs):
        if gather:
            x_ref, ord_ref, f_ref = refs[:3]
            rest = refs[3:]
            v = jnp.take(x_ref[:], ord_ref[:])
        else:
            v_ref, f_ref = refs[:2]
            rest = refs[2:]
            v = v_ref[:]
        if scan_np is not None:
            v = v.astype(scan_np)
        f = f_ref[:]
        if not blocked:
            o_ref = rest[0]
            _pf, s = jax.lax.associative_scan(combine, (f, v))
            o_ref[:] = s
            return
        ident_ref, o_ref, cf_ref, cv_ref = rest

        @pl.when(pl.program_id(0) == 0)
        def _():
            cf_ref[0] = False
            cv_ref[0] = ident_ref[0]
        pf, pv = jax.lax.associative_scan(combine, (f, v))
        cf = jnp.broadcast_to(cf_ref[0], pf.shape)
        cv = jnp.broadcast_to(cv_ref[0], pv.shape)
        of, ov = combine((cf, cv), (pf, pv))
        o_ref[:] = ov
        cf_ref[0] = of[-1]
        cv_ref[0] = ov[-1]
    return kernel


def _run(new: jnp.ndarray, op_key: str, identity, out_np,
         x_sorted: Optional[jnp.ndarray] = None,
         x_full: Optional[jnp.ndarray] = None,
         order: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    op = _OPS[op_key]
    gather = x_full is not None
    src = x_full if gather else x_sorted
    cap = new.shape[0]
    scan_np = np.dtype(out_np) if out_np is not None and \
        np.dtype(out_np) != src.dtype else None
    out_dt = np.dtype(out_np) if out_np is not None else src.dtype
    # mirror exec/scans.seg_scan: one full-array scan for narrow dtypes
    # or small caps, 2^15 blocks + carry otherwise (float bit-parity)
    blocked = out_dt.itemsize >= 8 and cap > _BLOCK
    B = _BLOCK if blocked else cap
    kernel = _seg_kernel(op, B, blocked, gather, scan_np)

    if gather:
        n_src = src.shape[0]
        in_specs = [pl.BlockSpec((n_src,), lambda i: (0,)),
                    pl.BlockSpec((B,), lambda i: (i,)),
                    pl.BlockSpec((B,), lambda i: (i,))]
        args = [src, order, new]
    else:
        in_specs = [pl.BlockSpec((B,), lambda i: (i,)),
                    pl.BlockSpec((B,), lambda i: (i,))]
        args = [src, new]
    scratch = []
    if blocked:
        in_specs.append(pl.BlockSpec((1,), lambda i: (0,)))
        args.append(jnp.full((1,), identity, dtype=out_dt))
        scratch = [pltpu.SMEM((1,), jnp.bool_),
                   pltpu.SMEM((1,), out_dt)]
    return pl.pallas_call(
        kernel,
        grid=(cap // B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((B,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((cap,), out_dt),
        scratch_shapes=scratch,
        interpret=kb.interpret(),
    )(*args)


def seg_scan_sorted(new: jnp.ndarray, x_sorted: jnp.ndarray,
                    op_key: str, identity) -> jnp.ndarray:
    """Inclusive segmented scan over already-sorted values — the
    Pallas counterpart of ``exec/scans.seg_scan`` (identical combine
    structure, fused into one pass)."""
    return _run(new, op_key, identity, None, x_sorted=x_sorted)


def gather_seg_scan(x_masked: jnp.ndarray, order: jnp.ndarray,
                    new: jnp.ndarray, op_key: str, identity,
                    scan_np=None) -> jnp.ndarray:
    """Single-pass sorted-order gather + segmented scan: ``x_masked``
    stays in ORIGINAL row space (the caller pre-masks with the op's
    identity there, exactly like the XLA path) and is gathered through
    ``order`` block by block, feeding the in-block scan directly — the
    sorted copy and the standalone scan array never materialize.
    ``scan_np`` widens AFTER the gather (narrow gathers are 3x cheaper
    than emulated-i64 ones; the cast ordering matches
    ``_SortedCtx.seg_sum``)."""
    return _run(new, op_key, identity, scan_np, x_full=x_masked,
                order=order)
