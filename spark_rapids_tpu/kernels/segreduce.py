"""Kernel 3: single-pass segmented reduction (Pallas).

The sorted-key grouped aggregate (exec/tpu_aggregate.py) computes every
reduction as a composed chain over [cap]-sized intermediates: gather
values into sorted order (``take_sorted`` — one full materialized
copy), run a cumsum or blocked segmented scan over that copy (a second
full traversal writing a third array), then gather group ends.  On the
gather-bound chip that chain IS the measured ~82 ms q6 aggregate wall
(PERF.md round-5 stage differencing).

This kernel fuses the first two stages into ONE sequential pass:
per block, the sorted-order gather feeds the in-block segmented
``associative_scan`` directly (values never round-trip through HBM as
a sorted copy), and a (flag, value) carry in SMEM scratch threads the
running segment prefix across blocks.  The block size and combine
structure mirror ``exec/scans.seg_scan`` EXACTLY (one
``associative_scan`` per 2^15-element block, elementwise carry
combine; a single full-array scan below that size or for narrow
dtypes) so float results are bit-identical to the XLA path — float
addition is the one order-sensitive op, and an identical reduction
tree is the parity contract CI enforces.

Per-kernel fallback (``kernel.backend.pallas.fallbacks.agg.segreduce.*``):
2-D payloads (string byte matrices), unknown ops, and capacities off
the block grid take the existing XLA formulation for that reduction
only.  Selection happens while TRACING the cached aggregate kernel, so
hits/fallbacks count once per compile; per-dispatch attribution is the
``kernel.dispatches.agg_*.{pallas|xla}`` counters.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu.kernels import backend as kb
from spark_rapids_tpu.kernels import tiling

_BLOCK = 1 << 15          # MUST match exec/scans._BLOCK (float parity)

_OPS = {"add": jnp.add, "min": jnp.minimum, "max": jnp.maximum}


def op_name(op) -> Optional[str]:
    if op is jnp.add:
        return "add"
    if op is jnp.minimum:
        return "min"
    if op is jnp.maximum:
        return "max"
    return None


# element-block ceiling of the NON-blocked scan path (bytes): narrow
# out dtypes (< 8-byte — int32 counts/narrow sums, f32) run ONE
# full-array associative_scan, exactly mirroring exec/scans.seg_scan,
# so their element block is cap-sized and cannot tile without changing
# the scan tree (float parity).  Streaming removed the SOURCE gate
# (src_too_large, retired — sources of any size tile through VMEM);
# this bound only keeps the un-tileable cap-sized blocks of the narrow
# path within the old envelope, with its own reason tag.
_NARROW_BLOCK_MAX_BYTES = 64 << 20


def supported(cap: int, dtype, op: Optional[str], ndim: int = 1
              ) -> Tuple[bool, str]:
    if op is None:
        return False, "op"
    if ndim != 1:
        return False, "ndim"
    dt_ = np.dtype(dtype)
    if dt_.kind not in "iufb":
        return False, "dtype"
    if not (cap <= _BLOCK or cap % _BLOCK == 0):
        return False, "shape"
    # no SOURCE size gate: the gather path streams the source array
    # through VMEM in kernel.pallas.tileBytes tiles (the retired
    # src_too_large residency fallback; kernel.pallas.tiles.* counts
    # the streamed volume).  Narrow dtypes scan un-blocked (cap-sized
    # element blocks — see _NARROW_BLOCK_MAX_BYTES).
    if dt_.itemsize < 8 and cap > _BLOCK and \
            cap * dt_.itemsize > _NARROW_BLOCK_MAX_BYTES:
        return False, "wide_block"
    return True, ""


def _combine(op):
    def combine(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, op(va, vb))
    return combine


def _seg_kernel(op, B: int, blocked: bool, scan_np):
    """Sorted-path kernel body (1D grid): in-block segmented scan
    [-> carry across blocks].  Blocked kernels take the op identity as
    a (1,)-shaped INPUT (last in_ref): it may be a traced value (e.g.
    the string-min word sentinel built under jit), which a closure
    constant could not carry."""
    from jax.experimental import pallas as pl
    combine = _combine(op)

    def kernel(*refs):
        v_ref, f_ref = refs[:2]
        rest = refs[2:]
        v = v_ref[:]
        if scan_np is not None:
            v = v.astype(scan_np)
        f = f_ref[:]
        if not blocked:
            o_ref = rest[0]
            _pf, s = jax.lax.associative_scan(combine, (f, v))
            o_ref[:] = s
            return
        ident_ref, o_ref, cf_ref, cv_ref = rest

        @pl.when(pl.program_id(0) == 0)
        def _():
            cf_ref[0] = False
            cv_ref[0] = ident_ref[0]
        pf, pv = jax.lax.associative_scan(combine, (f, v))
        cf = jnp.broadcast_to(cf_ref[0], pf.shape)
        cv = jnp.broadcast_to(cv_ref[0], pv.shape)
        of, ov = combine((cf, cv), (pf, pv))
        o_ref[:] = ov
        cf_ref[0] = of[-1]
        cv_ref[0] = ov[-1]
    return kernel


def _seg_gather_kernel(op, B: int, T: int, n_tiles: int, blocked: bool,
                       scan_np):
    """Gather-path kernel body (2D grid over element blocks x source
    tiles): the sorted-order gather accumulates into a VMEM scratch
    across the tile sweep — each lane's source index (a permutation
    entry) lands in exactly one tile, and ``pl.when`` skips tiles no
    lane of this block references — then the LAST tile runs the exact
    in-block segmented scan + (flag, value) SMEM carry of the sorted
    path.  The scan structure (one associative_scan per ``B`` block,
    elementwise carry combine, identical combine order) is untouched by
    the tiling — only WHERE the gathered operand block comes from
    changed — so float results stay bit-identical to exec/scans.seg_scan
    across tile boundaries, including segments spanning many tiles."""
    from jax.experimental import pallas as pl
    combine = _combine(op)

    def kernel(*refs):
        x_ref, ord_ref, f_ref = refs[:3]
        rest = refs[3:]
        if blocked:
            ident_ref, o_ref, vacc_ref, cf_ref, cv_ref = rest
        else:
            o_ref, vacc_ref = rest[0], rest[1]
        # program ids hoisted: interpret-mode lowering cannot rewrite
        # the primitive inside a pl.when sub-jaxpr
        i = pl.program_id(0)
        j = pl.program_id(1)
        o = ord_ref[:]
        lo = j * T
        in_tile = (o >= lo) & (o < lo + T)

        @pl.when(jnp.any(in_tile))
        def _():
            local = jnp.clip(o - lo, 0, T - 1).astype(jnp.int32)
            vals = jnp.take(x_ref[:], local)
            if n_tiles == 1:
                vacc_ref[:] = vals
            else:
                vacc_ref[:] = jnp.where(in_tile, vals, vacc_ref[:])

        @pl.when(j == n_tiles - 1)
        def _():
            v = vacc_ref[:]
            if scan_np is not None:
                v = v.astype(scan_np)
            f = f_ref[:]
            if not blocked:
                _pf, s = jax.lax.associative_scan(combine, (f, v))
                o_ref[:] = s
                return

            @pl.when(i == 0)
            def _():
                cf_ref[0] = False
                cv_ref[0] = ident_ref[0]
            pf, pv = jax.lax.associative_scan(combine, (f, v))
            cf = jnp.broadcast_to(cf_ref[0], pf.shape)
            cv = jnp.broadcast_to(cv_ref[0], pv.shape)
            of, ov = combine((cf, cv), (pf, pv))
            o_ref[:] = ov
            cf_ref[0] = of[-1]
            cv_ref[0] = ov[-1]
    return kernel


def _run(new: jnp.ndarray, op_key: str, identity, out_np,
         x_sorted: Optional[jnp.ndarray] = None,
         x_full: Optional[jnp.ndarray] = None,
         order: Optional[jnp.ndarray] = None,
         tile_bytes: Optional[int] = None) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    op = _OPS[op_key]
    gather = x_full is not None
    src = x_full if gather else x_sorted
    cap = new.shape[0]
    scan_np = np.dtype(out_np) if out_np is not None and \
        np.dtype(out_np) != src.dtype else None
    out_dt = np.dtype(out_np) if out_np is not None else src.dtype
    # mirror exec/scans.seg_scan: one full-array scan for narrow dtypes
    # or small caps, 2^15 blocks + carry otherwise (float bit-parity)
    blocked = out_dt.itemsize >= 8 and cap > _BLOCK
    B = _BLOCK if blocked else cap

    if not gather:
        # sorted path: the operand is already element-blocked; no large
        # resident source, 1D grid as before
        kernel = _seg_kernel(op, B, blocked, scan_np)
        in_specs = [pl.BlockSpec((B,), lambda i: (i,)),
                    pl.BlockSpec((B,), lambda i: (i,))]
        args = [src, new]
        scratch = []
        if blocked:
            in_specs.append(pl.BlockSpec((1,), lambda i: (0,)))
            args.append(jnp.full((1,), identity, dtype=out_dt))
            scratch = [pltpu.SMEM((1,), jnp.bool_),
                       pltpu.SMEM((1,), out_dt)]
        return pl.pallas_call(
            kernel,
            grid=(cap // B,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((B,), lambda i: (i,)),
            out_shape=jax.ShapeDtypeStruct((cap,), out_dt),
            scratch_shapes=scratch,
            interpret=kb.interpret(),
        )(*args)

    # gather path: stream the [n_src] value array through VMEM in
    # tiles (2D grid; the element block stays pinned at the sorted
    # path's B for the shared scan structure — float bit-parity)
    n_src = src.shape[0]
    isz = np.dtype(src.dtype).itemsize
    p = tiling.plan("agg.segreduce", cap, n_src, isz, B, block_max=B,
                    tile_bytes=tile_bytes)
    T, n_tiles = p.tile, p.n_tiles
    # selection happens at trace time of the enclosing cached aggregate
    # kernel, so tile volume counts once per compile (like kb.hit)
    kb.record_tiles("agg.segreduce", n_tiles, p.tile_nbytes)
    if p.src_pad != n_src:
        src = jnp.pad(src, (0, p.src_pad - n_src))
    kernel = _seg_gather_kernel(op, B, T, n_tiles, blocked, scan_np)
    in_specs = [pl.BlockSpec((T,), lambda i, j: (j,)),
                pl.BlockSpec((B,), lambda i, j: (i,)),
                pl.BlockSpec((B,), lambda i, j: (i,))]
    args = [src, order, new]
    scratch = [pltpu.VMEM((B,), src.dtype)]   # gather accumulator
    if blocked:
        in_specs.append(pl.BlockSpec((1,), lambda i, j: (0,)))
        args.append(jnp.full((1,), identity, dtype=out_dt))
        scratch = scratch + [pltpu.SMEM((1,), jnp.bool_),
                             pltpu.SMEM((1,), out_dt)]
    return pl.pallas_call(
        kernel,
        grid=(cap // B, n_tiles),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((B,), lambda i, j: (i,)),
        out_shape=jax.ShapeDtypeStruct((cap,), out_dt),
        scratch_shapes=scratch,
        interpret=kb.interpret(),
    )(*args)


def seg_scan_sorted(new: jnp.ndarray, x_sorted: jnp.ndarray,
                    op_key: str, identity) -> jnp.ndarray:
    """Inclusive segmented scan over already-sorted values — the
    Pallas counterpart of ``exec/scans.seg_scan`` (identical combine
    structure, fused into one pass)."""
    return _run(new, op_key, identity, None, x_sorted=x_sorted)


def gather_seg_scan(x_masked: jnp.ndarray, order: jnp.ndarray,
                    new: jnp.ndarray, op_key: str, identity,
                    scan_np=None,
                    tile_bytes: Optional[int] = None) -> jnp.ndarray:
    """Single-pass sorted-order gather + segmented scan: ``x_masked``
    stays in ORIGINAL row space (the caller pre-masks with the op's
    identity there, exactly like the XLA path) and is gathered through
    ``order`` block by block, feeding the in-block scan directly — the
    sorted copy and the standalone scan array never materialize.
    ``scan_np`` widens AFTER the gather (narrow gathers are 3x cheaper
    than emulated-i64 ones; the cast ordering matches
    ``_SortedCtx.seg_sum``).
    ``tile_bytes`` pins the source-tile budget the enclosing cached
    kernel keyed on (None = the live knob)."""
    return _run(new, op_key, identity, scan_np, x_full=x_masked,
                order=order, tile_bytes=tile_bytes)
