"""Kernel 1: dense phase-decomposed RLE/bit-unpack (Pallas).

The per-column Parquet decode path (io/device_parquet.py,
``expand_runs_matrix``) expands a hybrid RLE/bit-packed stream with
per-ELEMENT random work: a run-id lookup, four 4-byte window gathers
and ~5 run-metadata takes — ~9 gathers per element on a chip where
gathers run ~90M/s while dense vector ops stream at HBM bandwidth
(PERF.md round-4b cost model; "a dense phase-decomposed unpack is
future work").  This module is that future work:

  phase 0  ``unpack_bits`` — the whole packed byte buffer unpacks as
           ONE dense w-wide bitstring: bytes -> little-endian u32
           words -> per-value static (word, shift) slots.  A Pallas
           kernel over value blocks; ZERO gathers.
  phase 1  run metadata broadcasts to elements as two step functions
           (A = dense-index offset, C = RLE value*2+flag) via
           delta-scatter + cumsum — vector ops, zero gathers (the
           io/parquet_fused.py general-path formulation).
  phase 2  ``_expand`` — a Pallas kernel computes ``dense[A + i]`` per
           element with the step functions resident per block: ONE
           gather per element, into a dense value array.

Net: ~9 gathers/element -> 1 (``GATHERS_PER_ELEMENT`` below, asserted
by tests/test_kernels.py against the traced jaxpr of the XLA path).
The Pallas path also covers dictionary bit widths up to 32 — the XLA
window-gather path is capped at ``_MAX_W`` = 24 bits (4-byte window =
shift(<=7) + w), so widths 25-32 previously fell all the way back to
host Arrow decode; under ``kernel.backend=pallas`` they stay on
device (the per-kernel-fallback cliff the motivation cites).

Fallback matrix (reasons land in
``kernel.backend.pallas.fallbacks.decode.*``): mixed bit widths within
one stream, values too wide for the i32 step function, a dense buffer
past the residency gate, or shapes off the 32-value alignment grid.
Everything unsupported takes the existing XLA (or host) path for that
stream only.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu.kernels import backend as kb

# by-construction per-element gather counts of the two stream-expansion
# formulations (XLA's count is additionally measured from its traced
# jaxpr by tests/test_kernels.py and bench.py's kernels probe)
GATHERS_PER_ELEMENT = {"xla": 9, "pallas": 1}

_UNPACK_BLOCK = 8192      # values per grid step (phase 0)
_EXPAND_BLOCK = 8192      # elements per grid step (phase 2)
# dense-value residency gate for the expand kernel (bytes); streams
# past it fall back — on-hardware tiling of the dense buffer through
# the HBM->VMEM double-buffer pattern is the first follow-up there
_DENSE_MAX_BYTES = 64 << 20


# ---------------------------------------------------------------------------
# phase 0: dense bit-unpack
# ---------------------------------------------------------------------------

def _unpack_xla(bytes_arr: jnp.ndarray, w: int, ncap: int) -> jnp.ndarray:
    """Reference XLA unpack — the exact ``io/parquet_fused``
    formulation (moved here so both backends share one definition and
    the fused decode routes through the backend switch)."""
    if w == 1:
        bits = ((bytes_arr[:, None] >>
                 jnp.arange(8, dtype=jnp.uint8)) & 1)      # [B, 8]
        return bits.reshape(-1).astype(jnp.uint32)
    if ncap % 32 == 0 and bytes_arr.shape[0] % 4 == 0:
        words = (bytes_arr.reshape(-1, 4).astype(jnp.uint32) <<
                 jnp.arange(0, 32, 8, dtype=jnp.uint32)[None, :]
                 ).sum(axis=1, dtype=jnp.uint32)           # LE u32 words
        W = words.reshape(ncap // 32, w)
        mask = jnp.uint32((1 << w) - 1)
        outs = []
        for j in range(32):
            a, s = (j * w) >> 5, (j * w) & 31
            v = W[:, a] >> jnp.uint32(s)
            if s + w > 32:
                v = v | (W[:, a + 1] << jnp.uint32(32 - s))
            outs.append(v & mask)
        return jnp.stack(outs, axis=1).reshape(-1)
    bits = ((bytes_arr[:, None] >>
             jnp.arange(8, dtype=jnp.uint8)) & 1)          # [B, 8]
    vals = bits.reshape(ncap, w).astype(jnp.uint32)
    return jnp.sum(vals << jnp.arange(w, dtype=jnp.uint32)[None, :],
                   axis=1)


def _unpack_body(w: int, B: int):
    """Pallas kernel body for one [B]-value block: bytes -> LE u32
    words -> static (word, shift) slots — bit-identical integer math to
    ``_unpack_xla``'s word path, zero gathers."""
    def kernel(b_ref, o_ref):
        by = b_ref[:]
        # byte->LE-word shifts built with an in-kernel iota: a closure
        # constant array would be a captured value pallas_call rejects
        sh = jax.lax.broadcasted_iota(jnp.uint32, (1, 4), 1) * \
            jnp.uint32(8)
        words = (by.reshape(-1, 4).astype(jnp.uint32) << sh
                 ).sum(axis=1, dtype=jnp.uint32)
        W = words.reshape(B // 32, w)
        mask = jnp.uint32((1 << w) - 1)
        outs = []
        for j in range(32):
            a, s = (j * w) >> 5, (j * w) & 31
            v = W[:, a] >> jnp.uint32(s)
            if s + w > 32:
                v = v | (W[:, a + 1] << jnp.uint32(32 - s))
            outs.append(v & mask)
        o_ref[:] = jnp.stack(outs, axis=1).reshape(-1)
    return kernel


def _unpack_pallas(bytes_arr: jnp.ndarray, w: int,
                   ncap: int) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    B = min(ncap, _UNPACK_BLOCK)
    bpb = B * w // 8                  # bytes per block
    return pl.pallas_call(
        _unpack_body(w, B),
        grid=(ncap // B,),
        in_specs=[pl.BlockSpec((bpb,), lambda i: (i,))],
        out_specs=pl.BlockSpec((B,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((ncap,), jnp.uint32),
        interpret=kb.interpret(),
    )(bytes_arr)


def _unpack_supported(w: int, ncap: int, nbytes: int) -> bool:
    return (1 <= w <= 32 and ncap % 32 == 0 and
            ncap % min(ncap, _UNPACK_BLOCK) == 0 and
            nbytes == ncap * w // 8 and nbytes % 4 == 0)


def unpack_bits(bytes_arr: jnp.ndarray, w: int, ncap: int,
                backend: Optional[str] = None) -> jnp.ndarray:
    """Dense phase-0 unpack of one width's packed byte buffer to
    [ncap] uint32 — the backend switch for every caller (the fused
    whole-batch decode's per-width phase 0 and this module's phase 0).
    Integer-exact on both backends, so results are bit-identical by
    construction."""
    bk = kb.choose("decode.unpack", kb.resolve(backend),
                   _unpack_supported(w, ncap, bytes_arr.shape[0]),
                   reason="shape")
    if bk == kb.PALLAS:
        return _unpack_pallas(bytes_arr, w, ncap)
    return _unpack_xla(bytes_arr, w, ncap)


# ---------------------------------------------------------------------------
# phase 2: run expansion (one gather/element)
# ---------------------------------------------------------------------------

def _expand_body(B: int):
    from jax.experimental import pallas as pl

    def kernel(d_ref, a_ref, c_ref, o_ref):
        base = pl.program_id(0) * B
        i = jax.lax.broadcasted_iota(jnp.int32, (B, 1), 0)[:, 0] + base
        a = a_ref[:]
        c = c_ref[:]
        d = d_ref[:]
        idx = jnp.clip(a + i, 0, d.shape[0] - 1)
        vals = jnp.take(d, idx)     # the ONE per-element gather,
        #                             dense-value-resident per block
        o_ref[:] = jnp.where((c & 1) != 0, (c >> 1).astype(jnp.uint32),
                             vals)
    return kernel


def _expand_pallas(dense: jnp.ndarray, a: jnp.ndarray, c: jnp.ndarray,
                   cap: int) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    B = min(cap, _EXPAND_BLOCK)
    dlen = dense.shape[0]
    return pl.pallas_call(
        _expand_body(B),
        grid=(cap // B,),
        in_specs=[pl.BlockSpec((dlen,), lambda i: (0,)),
                  pl.BlockSpec((B,), lambda i: (i,)),
                  pl.BlockSpec((B,), lambda i: (i,))],
        out_specs=pl.BlockSpec((B,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((cap,), jnp.uint32),
        interpret=kb.interpret(),
    )(dense, a, c)


# ---------------------------------------------------------------------------
# host prep + public stream expansion
# ---------------------------------------------------------------------------

def stream_width(runs) -> Tuple[bool, int, str]:
    """(supported, width, reason): a stream is Pallas-expandable when
    its bit-packed runs share one NONZERO width <= 32.

    Width-0 bit-packed runs (a page written against a 1-entry
    dictionary) occupy zero packed bytes and decode to constant 0, so
    they don't constrain the dense width — ``_dense_meta`` rewrites
    them as RLE-0 runs.  Treating the accumulated 0 as "no width yet"
    while ALSO letting a 0-width run read ``bit_bases[i]//w`` would
    alias the NEXT run's packed values (a confirmed wrong-results
    repro), hence the explicit rewrite."""
    w = 0
    for i in range(len(runs.counts)):
        if runs.is_rle[i]:
            continue
        wi = int(runs.widths[i])
        if wi == 0:
            continue        # zero packed bytes; rewritten to RLE-0
        if w and wi != w:
            return False, 0, "mixed_widths"
        w = wi
        if wi > 32:
            return False, 0, "width"
    return True, w, ""


def _dense_meta(runs, w: int, rcap: int) -> np.ndarray:
    """Per-run (start, dA, dC) deltas — the step-function coefficients
    phase 1 scatters (O(runs) host work, like ``_upload_runs``).  ``A``
    carries through RLE runs so deltas telescope (the
    ``io/parquet_fused._stream_quads`` trick).  The matrix widens to
    int64 when a wide RLE payload (w approaching 32) overflows the i32
    step function — the kernel handles either dtype."""
    n = len(runs.counts)
    rows = []
    pos = 0
    prev_a = prev_c = 0
    lo = hi = 0
    for i in range(n):
        start = pos
        pos += int(runs.counts[i])
        if runs.is_rle[i]:
            a = prev_a
            c = (int(runs.values[i]) << 1) | 1
        elif int(runs.widths[i]) == 0:
            # width-0 bit-pack: zero packed bytes, every value is 0 —
            # an RLE-0 run (its bit_base//w would alias the NEXT run's
            # values; see stream_width)
            a = prev_a
            c = 1
        else:
            valoff = int(runs.bit_bases[i]) // w if w else 0
            a = valoff - start
            c = 0
        rows.append((start, a - prev_a, c - prev_c))
        lo = min(lo, rows[-1][1], rows[-1][2])
        hi = max(hi, rows[-1][1], rows[-1][2])
        prev_a, prev_c = a, c
    np_t = np.int32 if -(1 << 31) <= lo and hi < (1 << 31) else np.int64
    mat = np.zeros((rcap, 3), dtype=np_t)
    mat[n:, 0] = np_t(1 << 30)          # padding rows: clipped + dropped
    for i, r in enumerate(rows):
        mat[i] = r
    return mat


def _expand_impl(w: int, ncap: int, cap: int):
    """Device half of the Pallas stream expansion (jitted once per
    (w, ncap, cap, interpret) via the kernel cache)."""
    def run(mat: jnp.ndarray, packed: jnp.ndarray) -> jnp.ndarray:
        if w:
            dense = _unpack_pallas(packed, w, ncap)
        else:
            # 0-bit streams (single-entry dictionary): every bit-packed
            # value is 0 by definition; no dense phase at all
            dense = jnp.zeros((32,), jnp.uint32)
        # delta-scatter + cumsum step functions (zero gathers); the
        # meta dtype widens to i64 only for wide RLE payloads, and the
        # cumsum sits at jit TOP LEVEL — never inside control flow
        # (the scoped-VMEM pair-lowering landmine, exec/scans.py)
        starts = jnp.minimum(mat[:, 0], cap)
        a = jnp.cumsum(jnp.zeros((cap,), mat.dtype).at[starts].add(
            mat[:, 1], mode="drop"))
        c = jnp.cumsum(jnp.zeros((cap,), mat.dtype).at[starts].add(
            mat[:, 2], mode="drop"))
        return _expand_pallas(dense, a, c, cap)
    return run


def expand_stream(runs, packed: bytes, cap: int,
                  backend: Optional[str] = None) -> jnp.ndarray:
    """Expand one hybrid RLE/bit-packed stream to [cap] uint32 on the
    selected backend (the per-column decode path's backend switch —
    io/device_parquet.decode_plan).

    Pallas: dense phase decomposition above, ONE gather/element, two
    uploads (run matrix + packed bytes — transfer parity with the XLA
    path).  XLA: the existing ``expand_runs_matrix`` window-gather
    formulation (~9 gathers/element), which additionally REQUIRES
    w <= ``_MAX_W`` (24) — wider streams raise ``UnsupportedChunk`` so
    the column takes the host-Arrow fallback, exactly as before this
    module existed."""
    from spark_rapids_tpu.columnar.batch import bucket_rows
    from spark_rapids_tpu.exec import kernel_cache as kc
    from spark_rapids_tpu.io import device_parquet as dp

    def xla_path():
        wmax = max((int(x) for x, r in zip(runs.widths, runs.is_rle)
                    if not r), default=0)
        if wmax > dp._MAX_W:
            # the XLA 4-byte-window formulation can't reach past 24
            # bits; raising keeps the pre-pallas per-column host
            # fallback behavior
            raise dp.UnsupportedChunk(f"dict bit width {wmax}")
        dev = dp._upload_runs(runs, packed)
        return dp._expand_runs_packed(dev["runs_mat"], dev["packed"],
                                      cap=cap)

    if kb.resolve(backend) != kb.PALLAS:
        # default path exits before any eligibility work: the support
        # walk below is O(runs) host time that only the pallas
        # decision consumes
        return xla_path()

    ok, w, reason = stream_width(runs)
    nvals = sum(int(c) for c, r in zip(runs.counts, runs.is_rle)
                if not r)
    ncap = bucket_rows(max(nvals, 1), 32)
    if ok and w:
        ok = _unpack_supported(w, ncap, ncap * w // 8) and \
            ncap * 4 <= _DENSE_MAX_BYTES
        reason = reason or ("dense_too_large"
                            if ncap * 4 > _DENSE_MAX_BYTES else "shape")
    if ok:
        ok = cap % min(cap, _EXPAND_BLOCK) == 0
        reason = reason or "shape"
    bk = kb.choose("decode.expand", kb.PALLAS, ok,
                   reason=reason or "unsupported")
    if bk != kb.PALLAS:
        return xla_path()

    rcap = bucket_rows(max(len(runs.counts), 1), 8)
    mat = _dense_meta(runs, w, rcap)
    pbytes = np.frombuffer(bytes(packed), dtype=np.uint8)
    packed_dev = jnp.asarray(dp._pad_np(pbytes, max(ncap * w // 8, 4)))
    kern = kc.get_kernel(
        ("decode_expand", kb.PALLAS, w, rcap, ncap, cap,
         str(mat.dtype), kb.interpret()),
        lambda: _expand_impl(w, ncap, cap),
        backend=kb.PALLAS)
    return kern(jnp.asarray(mat), packed_dev)
