"""Kernel 1: dense phase-decomposed RLE/bit-unpack (Pallas).

The per-column Parquet decode path (io/device_parquet.py,
``expand_runs_matrix``) expands a hybrid RLE/bit-packed stream with
per-ELEMENT random work: a run-id lookup, four 4-byte window gathers
and ~5 run-metadata takes — ~9 gathers per element on a chip where
gathers run ~90M/s while dense vector ops stream at HBM bandwidth
(PERF.md round-4b cost model; "a dense phase-decomposed unpack is
future work").  This module is that future work:

  phase 0  ``unpack_bits`` — the whole packed byte buffer unpacks as
           ONE dense w-wide bitstring: bytes -> little-endian u32
           words -> per-value static (word, shift) slots.  A Pallas
           kernel over value blocks; ZERO gathers.
  phase 1  run metadata broadcasts to elements as two step functions
           (A = dense-index offset, C = RLE value*2+flag) via
           delta-scatter + cumsum — vector ops, zero gathers (the
           io/parquet_fused.py general-path formulation).
  phase 2  ``_expand`` — a Pallas kernel computes ``dense[A + i]`` per
           element with the step functions resident per block: ONE
           gather per element, into a dense value array.

Net: ~9 gathers/element -> 1 (``GATHERS_PER_ELEMENT`` below, asserted
by tests/test_kernels.py against the traced jaxpr of the XLA path).
The Pallas path also covers dictionary bit widths up to 32 — the XLA
window-gather path is capped at ``_MAX_W`` = 24 bits (4-byte window =
shift(<=7) + w), so widths 25-32 previously fell all the way back to
host Arrow decode; under ``kernel.backend=pallas`` they stay on
device (the per-kernel-fallback cliff the motivation cites).

Arbitrarily large dense-value buffers STREAM through the expand kernel
(kernels/tiling.py): the grid gains a second dimension over fixed-size
dense tiles (``kernel.pallas.tileBytes``), the output block stays
VMEM-resident across the tile sweep, and each tile's gather runs only
under ``pl.when`` when some element of the block actually indexes into
it — the dense index of a hybrid stream is monotone non-decreasing, so
almost every (block, tile) cell skips.  This replaced the PR 9 64 MiB
``dense_too_large`` residency fallback; tile volume is observable as
``kernel.pallas.tiles.decode.expand``.

Fallback matrix (reasons land in
``kernel.backend.pallas.fallbacks.decode.*``): mixed bit widths within
one stream, values too wide for the i32 step function, or shapes off
the 32-value alignment grid.  Everything unsupported takes the
existing XLA (or host) path for that stream only.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu.kernels import backend as kb
from spark_rapids_tpu.kernels import tiling

# by-construction per-element gather counts of the two stream-expansion
# formulations (XLA's count is additionally measured from its traced
# jaxpr by tests/test_kernels.py and bench.py's kernels probe)
GATHERS_PER_ELEMENT = {"xla": 9, "pallas": 1}

_UNPACK_BLOCK = 8192      # base values per grid step (phase 0)
_EXPAND_BLOCK = 8192      # base elements per grid step (phase 2)


# ---------------------------------------------------------------------------
# phase 0: dense bit-unpack
# ---------------------------------------------------------------------------

def _unpack_xla(bytes_arr: jnp.ndarray, w: int, ncap: int) -> jnp.ndarray:
    """Reference XLA unpack — the exact ``io/parquet_fused``
    formulation (moved here so both backends share one definition and
    the fused decode routes through the backend switch)."""
    if w == 1:
        bits = ((bytes_arr[:, None] >>
                 jnp.arange(8, dtype=jnp.uint8)) & 1)      # [B, 8]
        return bits.reshape(-1).astype(jnp.uint32)
    if ncap % 32 == 0 and bytes_arr.shape[0] % 4 == 0:
        words = (bytes_arr.reshape(-1, 4).astype(jnp.uint32) <<
                 jnp.arange(0, 32, 8, dtype=jnp.uint32)[None, :]
                 ).sum(axis=1, dtype=jnp.uint32)           # LE u32 words
        W = words.reshape(ncap // 32, w)
        mask = jnp.uint32((1 << w) - 1)
        outs = []
        for j in range(32):
            a, s = (j * w) >> 5, (j * w) & 31
            v = W[:, a] >> jnp.uint32(s)
            if s + w > 32:
                v = v | (W[:, a + 1] << jnp.uint32(32 - s))
            outs.append(v & mask)
        return jnp.stack(outs, axis=1).reshape(-1)
    bits = ((bytes_arr[:, None] >>
             jnp.arange(8, dtype=jnp.uint8)) & 1)          # [B, 8]
    vals = bits.reshape(ncap, w).astype(jnp.uint32)
    return jnp.sum(vals << jnp.arange(w, dtype=jnp.uint32)[None, :],
                   axis=1)


def _unpack_body(w: int, B: int):
    """Pallas kernel body for one [B]-value block: bytes -> LE u32
    words -> static (word, shift) slots — bit-identical integer math to
    ``_unpack_xla``'s word path, zero gathers."""
    def kernel(b_ref, o_ref):
        by = b_ref[:]
        # byte->LE-word shifts built with an in-kernel iota: a closure
        # constant array would be a captured value pallas_call rejects
        sh = jax.lax.broadcasted_iota(jnp.uint32, (1, 4), 1) * \
            jnp.uint32(8)
        words = (by.reshape(-1, 4).astype(jnp.uint32) << sh
                 ).sum(axis=1, dtype=jnp.uint32)
        W = words.reshape(B // 32, w)
        mask = jnp.uint32((1 << w) - 1)
        outs = []
        for j in range(32):
            a, s = (j * w) >> 5, (j * w) & 31
            v = W[:, a] >> jnp.uint32(s)
            if s + w > 32:
                v = v | (W[:, a + 1] << jnp.uint32(32 - s))
            outs.append(v & mask)
        o_ref[:] = jnp.stack(outs, axis=1).reshape(-1)
    return kernel


def _unpack_block(ncap: int) -> int:
    """Adaptive phase-0 block: pow2, grows with ncap (bounded grid —
    a 16M-value buffer is a 128-cell grid, not 2048) while staying on
    the 32-value alignment the (word, shift) slot table needs."""
    return tiling.plan("decode.unpack", ncap, 1, 1, _UNPACK_BLOCK).block


def _unpack_pallas(bytes_arr: jnp.ndarray, w: int,
                   ncap: int) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    B = min(ncap, _unpack_block(ncap))
    bpb = B * w // 8                  # bytes per block
    return pl.pallas_call(
        _unpack_body(w, B),
        grid=(ncap // B,),
        in_specs=[pl.BlockSpec((bpb,), lambda i: (i,))],
        out_specs=pl.BlockSpec((B,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((ncap,), jnp.uint32),
        interpret=kb.interpret(),
    )(bytes_arr)


def _unpack_supported(w: int, ncap: int, nbytes: int) -> bool:
    return (1 <= w <= 32 and ncap % 32 == 0 and
            ncap % min(ncap, _unpack_block(ncap)) == 0 and
            nbytes == ncap * w // 8 and nbytes % 4 == 0)


def unpack_bits(bytes_arr: jnp.ndarray, w: int, ncap: int,
                backend: Optional[str] = None) -> jnp.ndarray:
    """Dense phase-0 unpack of one width's packed byte buffer to
    [ncap] uint32 — the backend switch for every caller (the fused
    whole-batch decode's per-width phase 0 and this module's phase 0).
    Integer-exact on both backends, so results are bit-identical by
    construction."""
    bk = kb.choose("decode.unpack", kb.resolve(backend),
                   _unpack_supported(w, ncap, bytes_arr.shape[0]),
                   reason="shape")
    if bk == kb.PALLAS:
        return _unpack_pallas(bytes_arr, w, ncap)
    return _unpack_xla(bytes_arr, w, ncap)


# ---------------------------------------------------------------------------
# phase 2: run expansion (one gather/element, dense tiles streamed)
# ---------------------------------------------------------------------------

def _expand_body(B: int, T: int, dlen: int):
    """2D-grid kernel body: element block i against dense tile j.

    The output block is VMEM-resident across the whole tile sweep
    (its index map ignores j): j == 0 writes the RLE lanes and zeros,
    each tile then overwrites exactly the bit-packed lanes whose
    (clipped) dense index falls inside it — the index is unique per
    lane, so accumulation is a plain masked select, and the gather is
    ``pl.when``-elided for tiles no lane of this block references
    (monotone dense indices make that the overwhelming case)."""
    from jax.experimental import pallas as pl

    def kernel(d_ref, a_ref, c_ref, o_ref):
        base = pl.program_id(0) * B
        j = pl.program_id(1)
        i = jax.lax.broadcasted_iota(jnp.int32, (B, 1), 0)[:, 0] + base
        a = a_ref[:]
        c = c_ref[:]
        rle = (c & 1) != 0
        # the clip mirrors the untiled formulation exactly: padding
        # lanes ride the last run's step function past dlen and land
        # (clipped) in the final tile, same value as before tiling
        idx = jnp.clip(a + i, 0, dlen - 1)

        @pl.when(j == 0)
        def _():
            o_ref[:] = jnp.where(rle, (c >> 1).astype(jnp.uint32),
                                 jnp.uint32(0))

        lo = j * T
        in_tile = jnp.logical_not(rle) & (idx >= lo) & (idx < lo + T)

        @pl.when(jnp.any(in_tile))
        def _():
            local = jnp.clip(idx - lo, 0, T - 1).astype(jnp.int32)
            vals = jnp.take(d_ref[:], local)   # the ONE per-element
            #                                    gather, tile-resident
            o_ref[:] = jnp.where(in_tile, vals, o_ref[:])
    return kernel


def _expand_pallas(dense: jnp.ndarray, a: jnp.ndarray, c: jnp.ndarray,
                   cap: int,
                   p: "tiling.TilePlan | None" = None) -> jnp.ndarray:
    from jax.experimental import pallas as pl
    dlen = dense.shape[0]
    if p is None:
        p = tiling.plan("decode.expand", cap, dlen, 4, _EXPAND_BLOCK)
    B, T = p.block, p.tile
    if p.src_pad != dlen:
        # ragged final tile: pad the dense buffer to the tile grid (a
        # dense device-side pad); pad lanes are reachable only through
        # the clip, which in_tile already restricts to < dlen
        dense = jnp.pad(dense, (0, p.src_pad - dlen))
    return pl.pallas_call(
        _expand_body(B, T, dlen),
        grid=(cap // B, p.n_tiles),
        in_specs=[pl.BlockSpec((T,), lambda i, j: (j,)),
                  pl.BlockSpec((B,), lambda i, j: (i,)),
                  pl.BlockSpec((B,), lambda i, j: (i,))],
        out_specs=pl.BlockSpec((B,), lambda i, j: (i,)),
        out_shape=jax.ShapeDtypeStruct((cap,), jnp.uint32),
        interpret=kb.interpret(),
    )(dense, a, c)


# ---------------------------------------------------------------------------
# host prep + public stream expansion
# ---------------------------------------------------------------------------

def stream_width(runs) -> Tuple[bool, int, str]:
    """(supported, width, reason): a stream is Pallas-expandable when
    its bit-packed runs share one NONZERO width <= 32.

    Width-0 bit-packed runs (a page written against a 1-entry
    dictionary) occupy zero packed bytes and decode to constant 0, so
    they don't constrain the dense width — ``_dense_meta`` rewrites
    them as RLE-0 runs.  Treating the accumulated 0 as "no width yet"
    while ALSO letting a 0-width run read ``bit_bases[i]//w`` would
    alias the NEXT run's packed values (a confirmed wrong-results
    repro), hence the explicit rewrite."""
    w = 0
    for i in range(len(runs.counts)):
        if runs.is_rle[i]:
            continue
        wi = int(runs.widths[i])
        if wi == 0:
            continue        # zero packed bytes; rewritten to RLE-0
        if w and wi != w:
            return False, 0, "mixed_widths"
        w = wi
        if wi > 32:
            return False, 0, "width"
    return True, w, ""


def _dense_meta(runs, w: int, rcap: int) -> np.ndarray:
    """Per-run (start, dA, dC) deltas — the step-function coefficients
    phase 1 scatters (O(runs) host work, like ``_upload_runs``).  ``A``
    carries through RLE runs so deltas telescope (the
    ``io/parquet_fused._stream_quads`` trick).  The matrix widens to
    int64 when a wide RLE payload (w approaching 32) overflows the i32
    step function — the kernel handles either dtype."""
    n = len(runs.counts)
    rows = []
    pos = 0
    prev_a = prev_c = 0
    lo = hi = 0
    for i in range(n):
        start = pos
        pos += int(runs.counts[i])
        if runs.is_rle[i]:
            a = prev_a
            c = (int(runs.values[i]) << 1) | 1
        elif int(runs.widths[i]) == 0:
            # width-0 bit-pack: zero packed bytes, every value is 0 —
            # an RLE-0 run (its bit_base//w would alias the NEXT run's
            # values; see stream_width)
            a = prev_a
            c = 1
        else:
            valoff = int(runs.bit_bases[i]) // w if w else 0
            a = valoff - start
            c = 0
        rows.append((start, a - prev_a, c - prev_c))
        lo = min(lo, rows[-1][1], rows[-1][2])
        hi = max(hi, rows[-1][1], rows[-1][2])
        prev_a, prev_c = a, c
    np_t = np.int32 if -(1 << 31) <= lo and hi < (1 << 31) else np.int64
    mat = np.zeros((rcap, 3), dtype=np_t)
    mat[n:, 0] = np_t(1 << 30)          # padding rows: clipped + dropped
    for i, r in enumerate(rows):
        mat[i] = r
    return mat


def _expand_impl(w: int, ncap: int, cap: int, plan=None):
    """Device half of the Pallas stream expansion (jitted once per
    (w, ncap, cap, interpret, block, tile) via the kernel cache).
    ``plan`` is the tile plan the CALLER keyed the kernel on — trace
    time must use exactly that geometry, not a fresh read of the
    process tileBytes knob."""
    def run(mat: jnp.ndarray, packed: jnp.ndarray) -> jnp.ndarray:
        if w:
            dense = _unpack_pallas(packed, w, ncap)
        else:
            # 0-bit streams (single-entry dictionary): every bit-packed
            # value is 0 by definition; no dense phase at all
            dense = jnp.zeros((32,), jnp.uint32)
        # delta-scatter + cumsum step functions (zero gathers); the
        # meta dtype widens to i64 only for wide RLE payloads, and the
        # cumsum sits at jit TOP LEVEL — never inside control flow
        # (the scoped-VMEM pair-lowering landmine, exec/scans.py)
        starts = jnp.minimum(mat[:, 0], cap)
        a = jnp.cumsum(jnp.zeros((cap,), mat.dtype).at[starts].add(
            mat[:, 1], mode="drop"))
        c = jnp.cumsum(jnp.zeros((cap,), mat.dtype).at[starts].add(
            mat[:, 2], mode="drop"))
        return _expand_pallas(dense, a, c, cap, p=plan)
    return run


def expand_stream(runs, packed: bytes, cap: int,
                  backend: Optional[str] = None) -> jnp.ndarray:
    """Expand one hybrid RLE/bit-packed stream to [cap] uint32 on the
    selected backend (the per-column decode path's backend switch —
    io/device_parquet.decode_plan).

    Pallas: dense phase decomposition above, ONE gather/element, two
    uploads (run matrix + packed bytes — transfer parity with the XLA
    path).  XLA: the existing ``expand_runs_matrix`` window-gather
    formulation (~9 gathers/element), which additionally REQUIRES
    w <= ``_MAX_W`` (24) — wider streams raise ``UnsupportedChunk`` so
    the column takes the host-Arrow fallback, exactly as before this
    module existed."""
    from spark_rapids_tpu.columnar.batch import bucket_rows
    from spark_rapids_tpu.exec import kernel_cache as kc
    from spark_rapids_tpu.io import device_parquet as dp

    def xla_path():
        wmax = max((int(x) for x, r in zip(runs.widths, runs.is_rle)
                    if not r), default=0)
        if wmax > dp._MAX_W:
            # the XLA 4-byte-window formulation can't reach past 24
            # bits; raising keeps the pre-pallas per-column host
            # fallback behavior
            raise dp.UnsupportedChunk(f"dict bit width {wmax}")
        dev = dp._upload_runs(runs, packed)
        return dp._expand_runs_packed(dev["runs_mat"], dev["packed"],
                                      cap=cap)

    if kb.resolve(backend) != kb.PALLAS:
        # default path exits before any eligibility work: the support
        # walk below is O(runs) host time that only the pallas
        # decision consumes
        return xla_path()

    ok, w, reason = stream_width(runs)
    nvals = sum(int(c) for c, r in zip(runs.counts, runs.is_rle)
                if not r)
    ncap = bucket_rows(max(nvals, 1), 32)
    if ok and w:
        ok = _unpack_supported(w, ncap, ncap * w // 8)
        reason = reason or "shape"
    # tile plan for the dense gather source (the streaming replacement
    # for the retired 64 MiB dense_too_large residency gate); its
    # block/tile shapes join the kernel key — derived from tier-
    # bucketed caps + the process tileBytes, so keys stay coarse
    p = tiling.plan("decode.expand", cap, max(ncap, 32) if w else 32,
                    4, _EXPAND_BLOCK)
    if ok:
        ok = cap % p.block == 0
        reason = reason or "shape"
    bk = kb.choose("decode.expand", kb.PALLAS, ok,
                   reason=reason or "unsupported")
    if bk != kb.PALLAS:
        return xla_path()
    kb.record_tiles("decode.expand", p.n_tiles, p.tile_nbytes)

    rcap = bucket_rows(max(len(runs.counts), 1), 8)
    mat = _dense_meta(runs, w, rcap)
    pbytes = np.frombuffer(bytes(packed), dtype=np.uint8)
    packed_dev = jnp.asarray(dp._pad_np(pbytes, max(ncap * w // 8, 4)))
    kern = kc.get_kernel(
        ("decode_expand", kb.PALLAS, w, rcap, ncap, cap,
         str(mat.dtype), kb.interpret(), p.block, p.tile),
        lambda: _expand_impl(w, ncap, cap, plan=p),
        backend=kb.PALLAS)
    return kern(jnp.asarray(mat), packed_dev)
