"""Kernel 2: fused dictionary-decode + filter (Pallas).

A filtering consumer directly above a parquet scan (q6 shape:
scan -> [fused filter] -> aggregate) pays the dictionary gather for
EVERY row at decode time and then drops most of them — for the bench's
25%-selective filter, 3 of every 4 dictionary lookups are wasted
gather bandwidth on a chip where gathers are the measured wall
(PERF.md).  When the planner pushes the consumer's combined condition
into the scan (plan/overrides._push_scan_filters), the fused decode
keeps dictionary columns as CODES through definition-level handling
and row-group stitching, evaluates the condition on the (fully
decoded, never-deferred) operand columns, and only then runs this
kernel: a PREDICATED dictionary gather that skips whole blocks in
which every row failed the filter — filtered-out rows never
materialize decoded values (their slots hold zeros; the consumer
re-applies the same mask, so downstream never observes them).

The block-skip is the Pallas-only part: ``@pl.when(any(keep))`` elides
the gather for all-dropped blocks, which no composed XLA formulation
can express (XLA's ``where`` still evaluates both arms).  Selection
and accounting happen host-side at scan-prepare time
(io/parquet_fused.py): per-batch ``kernel.backend.pallas.hits`` /
``.fallbacks.scan.filterDecode.*`` counters, per-kernel fallback to
the ordinary decode-everything path.

Arbitrarily large dictionaries STREAM through the kernels
(kernels/tiling.py): a second grid dimension walks the dictionary in
``kernel.pallas.tileBytes`` tiles, the output block stays VMEM-
resident across the sweep, and the per-tile gather is doubly
predicated — skipped when every row of the block failed the filter
AND when no surviving row's code lands in this tile.  This replaced
the PR 9 16 MiB ``dict_too_large`` residency fallback
(``kernel.pallas.tiles.scan.filterDecode`` counts streamed volume).

STRING dictionaries defer the same way (the widest decode cost in the
compile-bill top-10 is string-keyed): the fused decode stitches three
int32 code arrays per deferred string column — per-row byte base into
the shared u8 dictionary matrix buffer, per-row index into the
dictionary-lengths buffer, and the segment's static row stride — and
post-filter :func:`decode_str_pallas` gathers the byte matrix tile-
wise (each (row, char) cell predicated into its tile) while
:func:`decode_pallas` over the int32 lengths buffer recovers per-row
lengths.  Layouts the string tiler can't express (a row stride too
wide for even the minimum element block's 2-D VMEM footprint) fall
back per batch with reason ``string_layout``.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu.kernels import backend as kb
from spark_rapids_tpu.kernels import tiling

_BLOCK = 2048
# minimum element block of the 2-D string gather: below this the grid
# degenerates (and TPU sublane tiling would pad anyway) — a row stride
# that cannot fit _STR_MIN_BLOCK rows in a tile budget is the one
# layout the string tiler refuses (reason ``string_layout``)
_STR_MIN_BLOCK = 8


def supported(cap: int) -> Tuple[bool, str]:
    # no dictionary-size gate: dictionaries past one tileBytes tile
    # stream through the 2D grid (the retired dict_too_large reason);
    # only the element-block grid must divide.  The block is a pure
    # function of cap (never of tileBytes), so this gate cannot drift
    # from trace-time geometry.
    B = _block(cap)
    if not (cap <= B or cap % B == 0):
        return False, "shape"
    return True, ""


def str_supported(cap: int, width: int,
                  tile_bytes: "int | None" = None) -> Tuple[bool, str]:
    """Per-batch eligibility of the deferred STRING decode: the 2-D
    output block (rows x width) must fit the tile budget at the
    minimum element block, and the row grid must divide.  Callers that
    gate at plan-assemble time must pass the SAME ``tile_bytes`` they
    later hand :func:`decode_str_pallas` (the fused plan stamps it)."""
    B = _str_block(cap, width, tile_bytes)
    if B < _STR_MIN_BLOCK:
        return False, "string_layout"
    if not (cap <= B or cap % B == 0):
        return False, "shape"
    return True, ""


def _block(cap: int) -> int:
    """Adaptive element block (pow2, bounded grid) for the 1-D gather."""
    return min(cap, tiling.plan("scan.filterDecode", cap, 1, 1,
                                _BLOCK).block)


def _str_block(cap: int, width: int,
               tile_bytes: "int | None" = None) -> int:
    """Element block of the 2-D string gather: bounded so the (B, width)
    u8 output block plus its i32 index/mask planes stay within the tile
    budget (~5 bytes per (row, char) cell)."""
    tb = int(tile_bytes) if tile_bytes is not None else kb.tile_bytes()
    budget = max(tb // max(width * 5, 1), 1)
    b = _STR_MIN_BLOCK
    while b * 2 <= min(budget, _BLOCK):
        b *= 2
    if b > budget:
        return 0
    return min(cap, b)


def decode_xla(dbuf: jnp.ndarray, codes: jnp.ndarray,
               keep: jnp.ndarray) -> jnp.ndarray:
    """Reference path (also the parity oracle): unpredicated gather +
    select."""
    idx = jnp.clip(codes, 0, dbuf.shape[0] - 1)
    vals = jnp.take(dbuf, idx)
    return jnp.where(keep, vals, jnp.zeros((), dbuf.dtype))


def decode_pallas(dbuf: jnp.ndarray, codes: jnp.ndarray,
                  keep: jnp.ndarray,
                  tile_bytes: "int | None" = None) -> jnp.ndarray:
    """Predicated dictionary gather, dictionary streamed tile-wise:
    one [cap]-element pass that gathers only in (block, tile) cells
    where at least one surviving row's code lands in the tile."""
    from jax.experimental import pallas as pl
    cap = codes.shape[0]
    dlen = dbuf.shape[0]
    B = _block(cap)
    p = tiling.plan("scan.filterDecode", cap, dlen,
                    np.dtype(dbuf.dtype).itemsize, B, block_max=B,
                    tile_bytes=tile_bytes)
    T, n_tiles = p.tile, p.n_tiles
    # runs at trace time of the enclosing fused-decode kernel: tile
    # volume counts once per compile (the kb.hit counting semantics)
    kb.record_tiles("scan.filterDecode", n_tiles, p.tile_nbytes)
    if p.src_pad != dlen:
        dbuf = jnp.pad(dbuf, (0, p.src_pad - dlen))
    # numpy scalar, not a traced 0-d array: a traced closure constant
    # would be a captured value pallas_call rejects
    zero = np.zeros((), np.dtype(dbuf.dtype))[()]

    def kernel(d_ref, c_ref, k_ref, o_ref):
        j = pl.program_id(1)
        k = k_ref[:]
        c = jnp.clip(c_ref[:], 0, dlen - 1)   # decode_xla's exact clip

        @pl.when(j == 0)
        def _():
            o_ref[:] = jnp.full((B,), zero)

        lo = j * T
        in_tile = k & (c >= lo) & (c < lo + T)

        @pl.when(jnp.any(in_tile))
        def _():
            local = jnp.clip(c - lo, 0, T - 1).astype(jnp.int32)
            vals = jnp.take(d_ref[:], local)
            o_ref[:] = jnp.where(in_tile, vals, o_ref[:])

    return pl.pallas_call(
        kernel,
        grid=(cap // B, n_tiles),
        in_specs=[pl.BlockSpec((T,), lambda i, j: (j,)),
                  pl.BlockSpec((B,), lambda i, j: (i,)),
                  pl.BlockSpec((B,), lambda i, j: (i,))],
        out_specs=pl.BlockSpec((B,), lambda i, j: (i,)),
        out_shape=jax.ShapeDtypeStruct((cap,), dbuf.dtype),
        interpret=kb.interpret(),
    )(dbuf, codes, keep)


def decode_str_pallas(dbuf: jnp.ndarray, byte_base: jnp.ndarray,
                      lw: jnp.ndarray, keep: jnp.ndarray,
                      width: int,
                      tile_bytes: "int | None" = None) -> jnp.ndarray:
    """Predicated STRING-dictionary byte gather, the u8 dictionary
    matrix buffer streamed tile-wise: surviving row r reads bytes
    ``dbuf[byte_base[r] : byte_base[r] + lw[r]]`` into out[r, :lw[r]]
    (``lw`` is the segment's static row stride, 0 past it and on
    dropped/invalid rows).  Each (row, char) cell is predicated into
    the tile holding its byte, so a row's bytes may span tiles freely
    and all-dropped blocks never gather at all."""
    from jax.experimental import pallas as pl
    cap = byte_base.shape[0]
    dlen = dbuf.shape[0]
    B = _str_block(cap, width, tile_bytes)
    assert B >= _STR_MIN_BLOCK, "caller must gate via str_supported"
    p = tiling.plan("scan.filterDecode.str", cap, dlen, 1, B,
                    block_max=B, tile_bytes=tile_bytes)
    T, n_tiles = p.tile, p.n_tiles
    kb.record_tiles("scan.filterDecode.str", n_tiles, p.tile_nbytes)
    if p.src_pad != dlen:
        dbuf = jnp.pad(dbuf, (0, p.src_pad - dlen))

    def kernel(d_ref, bb_ref, lw_ref, k_ref, o_ref):
        j = pl.program_id(1)
        col = jax.lax.broadcasted_iota(jnp.int32, (B, width), 1)
        bb = bb_ref[:]
        live = k_ref[:][:, None] & (col < lw_ref[:][:, None])
        bidx = jnp.clip(bb[:, None] + col, 0, dlen - 1)

        @pl.when(j == 0)
        def _():
            o_ref[:] = jnp.zeros((B, width), jnp.uint8)

        lo = j * T
        in_tile = live & (bidx >= lo) & (bidx < lo + T)

        @pl.when(jnp.any(in_tile))
        def _():
            local = jnp.clip(bidx - lo, 0, T - 1)
            vals = jnp.take(d_ref[:], local)
            o_ref[:] = jnp.where(in_tile, vals, o_ref[:])

    return pl.pallas_call(
        kernel,
        grid=(cap // B, n_tiles),
        in_specs=[pl.BlockSpec((T,), lambda i, j: (j,)),
                  pl.BlockSpec((B,), lambda i, j: (i,)),
                  pl.BlockSpec((B,), lambda i, j: (i,)),
                  pl.BlockSpec((B,), lambda i, j: (i,))],
        out_specs=pl.BlockSpec((B, width), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((cap, width), jnp.uint8),
        interpret=kb.interpret(),
    )(dbuf, byte_base, lw, keep)


def str_decode_xla(dbuf: jnp.ndarray, byte_base: jnp.ndarray,
                   lw: jnp.ndarray, keep: jnp.ndarray,
                   width: int) -> jnp.ndarray:
    """XLA oracle of :func:`decode_str_pallas` (tests/CI parity)."""
    col = jnp.arange(width, dtype=jnp.int32)[None, :]
    bidx = jnp.clip(byte_base[:, None] + col, 0, dbuf.shape[0] - 1)
    live = keep[:, None] & (col < lw[:, None])
    return jnp.where(live, jnp.take(dbuf, bidx), jnp.uint8(0))
