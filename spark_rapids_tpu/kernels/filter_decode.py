"""Kernel 2: fused dictionary-decode + filter (Pallas).

A filtering consumer directly above a parquet scan (q6 shape:
scan -> [fused filter] -> aggregate) pays the dictionary gather for
EVERY row at decode time and then drops most of them — for the bench's
25%-selective filter, 3 of every 4 dictionary lookups are wasted
gather bandwidth on a chip where gathers are the measured wall
(PERF.md).  When the planner pushes the consumer's combined condition
into the scan (plan/overrides._push_scan_filters), the fused decode
keeps dictionary columns as CODES through definition-level handling
and row-group stitching, evaluates the condition on the (fully
decoded, never-deferred) operand columns, and only then runs this
kernel: a PREDICATED dictionary gather that skips whole blocks in
which every row failed the filter — filtered-out rows never
materialize decoded values (their slots hold zeros; the consumer
re-applies the same mask, so downstream never observes them).

The block-skip is the Pallas-only part: ``@pl.when(any(keep))`` elides
the gather for all-dropped blocks, which no composed XLA formulation
can express (XLA's ``where`` still evaluates both arms).  Selection
and accounting happen host-side at scan-prepare time
(io/parquet_fused.py): per-batch ``kernel.backend.pallas.hits`` /
``.fallbacks.scan.filterDecode.*`` counters, per-kernel fallback to
the ordinary decode-everything path.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.kernels import backend as kb

_BLOCK = 2048
# dictionary-residency gate (bytes) — see the decode-kernel note about
# HBM->VMEM tiling as the on-hardware follow-up
_DICT_MAX_BYTES = 16 << 20


def supported(cap: int, dict_len: int, itemsize: int
              ) -> Tuple[bool, str]:
    if dict_len * itemsize > _DICT_MAX_BYTES:
        return False, "dict_too_large"
    if not (cap <= _BLOCK or cap % _BLOCK == 0):
        return False, "shape"
    return True, ""


def decode_xla(dbuf: jnp.ndarray, codes: jnp.ndarray,
               keep: jnp.ndarray) -> jnp.ndarray:
    """Reference path (also the parity oracle): unpredicated gather +
    select."""
    idx = jnp.clip(codes, 0, dbuf.shape[0] - 1)
    vals = jnp.take(dbuf, idx)
    return jnp.where(keep, vals, jnp.zeros((), dbuf.dtype))


def decode_pallas(dbuf: jnp.ndarray, codes: jnp.ndarray,
                  keep: jnp.ndarray) -> jnp.ndarray:
    """Predicated dictionary gather: one [cap]-element pass, gathers
    only in blocks with at least one surviving row."""
    from jax.experimental import pallas as pl
    import numpy as np
    cap = codes.shape[0]
    B = min(cap, _BLOCK)
    dlen = dbuf.shape[0]
    # numpy scalar, not a traced 0-d array: a traced closure constant
    # would be a captured value pallas_call rejects
    zero = np.zeros((), np.dtype(dbuf.dtype))[()]

    def kernel(d_ref, c_ref, k_ref, o_ref):
        k = k_ref[:]
        any_kept = jnp.any(k)

        @pl.when(any_kept)
        def _():
            idx = jnp.clip(c_ref[:], 0, dlen - 1)
            vals = jnp.take(d_ref[:], idx)
            o_ref[:] = jnp.where(k, vals, zero)

        @pl.when(jnp.logical_not(any_kept))
        def _():
            o_ref[:] = jnp.full((B,), zero)

    return pl.pallas_call(
        kernel,
        grid=(cap // B,),
        in_specs=[pl.BlockSpec((dlen,), lambda i: (0,)),
                  pl.BlockSpec((B,), lambda i: (i,)),
                  pl.BlockSpec((B,), lambda i: (i,))],
        out_specs=pl.BlockSpec((B,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((cap,), dbuf.dtype),
        interpret=kb.interpret(),
    )(dbuf, codes, keep)
