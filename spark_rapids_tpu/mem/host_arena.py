"""ctypes binding for the native host staging arena (native/arena.cpp).

Role analog: the RMM arena allocator + pinned host pool of the reference
(reference: GpuDeviceManager.scala:196-270), managing *host* staging memory
under TPU/XLA (which owns HBM itself).  Builds the shared library on first
use with g++; falls back to a pure-Python malloc-per-allocation shim if no
toolchain is available, keeping the API identical.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_LIB = None
_LIB_LOCK = threading.Lock()
_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native", "arena.cpp")


def _build_lib() -> Optional[ctypes.CDLL]:
    so_path = os.path.join(os.path.dirname(_SRC), "libarena.so")
    if not os.path.exists(so_path) or \
            os.path.getmtime(so_path) < os.path.getmtime(_SRC):
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 _SRC, "-o", so_path],
                check=True, capture_output=True)
        except (subprocess.CalledProcessError, FileNotFoundError):
            return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None
    lib.arena_create.restype = ctypes.c_void_p
    lib.arena_create.argtypes = [ctypes.c_size_t, ctypes.c_size_t]
    lib.arena_destroy.argtypes = [ctypes.c_void_p]
    lib.arena_alloc.restype = ctypes.c_void_p
    lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.arena_free.restype = ctypes.c_int
    lib.arena_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    for fn in ("arena_allocated", "arena_peak", "arena_capacity",
               "arena_largest_free"):
        getattr(lib, fn).restype = ctypes.c_size_t
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.arena_num_live.restype = ctypes.c_int
    lib.arena_num_live.argtypes = [ctypes.c_void_p]
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    global _LIB
    with _LIB_LOCK:
        if _LIB is None:
            _LIB = _build_lib() or False
    return _LIB or None


class ArenaAllocation:
    """One allocation; exposes a zero-copy numpy view."""

    def __init__(self, arena: "HostArena", ptr: int, size: int):
        self._arena = arena
        self._ptr = ptr
        self.size = size
        self._closed = False

    def as_numpy(self, dtype=np.uint8, shape=None) -> np.ndarray:
        assert not self._closed
        n = self.size // np.dtype(dtype).itemsize
        buf = (ctypes.c_char * self.size).from_address(self._ptr)
        arr = np.frombuffer(buf, dtype=dtype, count=n)
        return arr.reshape(shape) if shape is not None else arr

    def close(self) -> None:
        if not self._closed:
            self._arena._free(self._ptr)
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class HostArena:
    """First-fit host arena; alloc failure returns None (spill + retry)."""

    def __init__(self, capacity: int, alignment: int = 64):
        self.capacity = capacity
        self._lib = _get_lib()
        if self._lib is not None:
            self._handle = self._lib.arena_create(capacity, alignment)
            if not self._handle:
                raise MemoryError(f"cannot reserve {capacity} byte arena")
            self.native = True
        else:  # pure-python fallback: plain malloc per allocation
            self._handle = None
            self._fallback = {}
            self._fallback_bytes = 0
            self._peak = 0
            self.native = False
        self._lock = threading.Lock()

    def alloc(self, size: int) -> Optional[ArenaAllocation]:
        if self.native:
            ptr = self._lib.arena_alloc(self._handle, size)
            if not ptr:
                return None
            return ArenaAllocation(self, ptr, size)
        with self._lock:
            if self._fallback_bytes + size > self.capacity:
                return None
            buf = ctypes.create_string_buffer(size)
            ptr = ctypes.addressof(buf)
            self._fallback[ptr] = buf
            self._fallback_bytes += size
            self._peak = max(self._peak, self._fallback_bytes)
        return ArenaAllocation(self, ptr, size)

    def _free(self, ptr: int) -> None:
        if self.native:
            self._lib.arena_free(self._handle, ptr)
        else:
            with self._lock:
                buf = self._fallback.pop(ptr, None)
                if buf is not None:
                    self._fallback_bytes -= len(buf)

    @property
    def allocated(self) -> int:
        if self.native:
            return self._lib.arena_allocated(self._handle)
        return self._fallback_bytes

    @property
    def peak(self) -> int:
        if self.native:
            return self._lib.arena_peak(self._handle)
        return self._peak

    @property
    def largest_free(self) -> int:
        if self.native:
            return self._lib.arena_largest_free(self._handle)
        return self.capacity - self._fallback_bytes

    @property
    def num_live(self) -> int:
        if self.native:
            return self._lib.arena_num_live(self._handle)
        return len(self._fallback)

    def close(self) -> None:
        if self.native and self._handle:
            self._lib.arena_destroy(self._handle)
            self._handle = None
