"""Spill framework: Device -> Host -> Disk tiered batch storage.

Reference analog (SURVEY.md §2b): ``RapidsBufferCatalog`` chaining
Device/Host/Disk stores (RapidsBufferCatalog.scala:34-210,
RapidsBufferStore.scala:40-351), priority-ordered synchronous spill on
allocation failure (DeviceMemoryEventHandler.scala:42-70,
SpillPriorities.scala), and ``SpillableColumnarBatch`` handles that let
operators hold batches that remain spillable
(SpillableColumnarBatch.scala:169).

TPU adaptation: XLA owns the HBM allocator, so instead of an RMM callback
the catalog enforces a *budget*: every registered batch counts toward a
device-bytes ceiling, and crossing it (or an explicit ``spill_to_fit``)
synchronously spills lowest-priority buffers device->host->disk.  The host
tier stages its numpy copies inside the native HostArena
(mem/host_arena.py); overflowing the host budget falls through to disk
(.npz files under the spill dir, RapidsDiskStore analog).
"""

from __future__ import annotations

import enum
import heapq
import itertools
import os
import tempfile
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import dtypes as dt
from spark_rapids_tpu.columnar.batch import DeviceBatch, DeviceColumn
from spark_rapids_tpu.mem.host_arena import HostArena
from spark_rapids_tpu.obs import recorder as obsrec
from spark_rapids_tpu.obs import registry as obsreg


class StorageTier(enum.IntEnum):
    DEVICE = 0
    HOST = 1
    DISK = 2


# spill priorities (reference: SpillPriorities.scala)
ACTIVE_ON_DECK_PRIORITY = 1 << 40
ACTIVE_BATCHING_PRIORITY = 1 << 30
INPUT_FROM_SHUFFLE_PRIORITY = 0
OUTPUT_FOR_SHUFFLE_PRIORITY = -(1 << 30)
# grace-join build/probe partitions parked while another partition is
# being joined: the coldest data in the process — they spill first
GRACE_JOIN_PARTITION_PRIORITY = -(1 << 31)


@dataclass
class _HostPayload:
    """Host copy of a batch: numpy arrays (arena-backed when possible)."""

    names: List[str]
    dtypes: List[dt.DType]
    num_rows: int
    arrays: List[Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]]
    allocations: List = field(default_factory=list)

    def nbytes(self) -> int:
        total = 0
        for d, v, l in self.arrays:
            total += d.nbytes + v.nbytes + (l.nbytes if l is not None else 0)
        return total

    def close(self):
        self.arrays = []
        for a in self.allocations:
            a.close()
        self.allocations = []


class _Buffer:
    def __init__(self, buffer_id: int, batch: DeviceBatch, priority: int):
        self.id = buffer_id
        self.priority = priority
        self.tier = StorageTier.DEVICE
        self.device_batch: Optional[DeviceBatch] = batch
        self.host: Optional[_HostPayload] = None
        self.disk_path: Optional[str] = None
        self.size = batch.nbytes()
        # num_rows may be a traced device scalar (a jitted kernel's
        # output); int() here would block the whole async pipeline on a
        # synchronous device->host round trip per registered batch — the
        # r2 bench's dominant cost.  Defer the read to spill time, when
        # we download the data anyway.
        self._meta = (list(batch.names),
                      [c.dtype for c in batch.columns], batch.num_rows)
        self.lock = threading.Lock()
        self.closed = False

    @property
    def meta(self):
        names, dtypes, nr = self._meta
        if not isinstance(nr, (int, np.integer)):
            nr = int(nr)
            self._meta = (names, dtypes, nr)
        return (names, dtypes, nr)


class BufferCatalog:
    """Singleton-ish catalog managing registered spillable batches."""

    def __init__(self, device_budget: int = 4 << 30,
                 host_budget: int = 8 << 30,
                 spill_dir: Optional[str] = None,
                 host_arena: Optional[HostArena] = None):
        self.device_budget = device_budget
        self.host_budget = host_budget
        self.spill_dir = spill_dir or tempfile.mkdtemp(
            prefix="rapids_tpu_spill_")
        self.host_arena = host_arena or HostArena(
            min(host_budget, 1 << 30))
        self._buffers: Dict[int, _Buffer] = {}
        self._ids = itertools.count()
        # RLock: SpillableBatch.__del__ may fire during a GC triggered
        # inside a catalog method that already holds the lock
        self._lock = threading.RLock()
        self.device_bytes = 0
        self.host_bytes = 0
        self.spilled_device_bytes = 0  # metrics (memoryBytesSpilled analog)
        self.spilled_disk_bytes = 0
        self._hwm_trackers: List["HighWaterTracker"] = []

    # -- registration ------------------------------------------------------
    def register(self, batch: DeviceBatch,
                 priority: int = ACTIVE_BATCHING_PRIORITY
                 ) -> "SpillableBatch":
        with self._lock:
            bid = next(self._ids)
            buf = _Buffer(bid, batch, priority)
            self._buffers[bid] = buf
            self.device_bytes += buf.size
            self._note_device_bytes_locked()
        obsreg.get_registry().gauge_max("spill.deviceBytesHwm",
                                        self.device_bytes)
        self._maybe_spill()
        return SpillableBatch(self, bid)

    # -- per-window device-bytes high water (admission refinement) ---------
    def _note_device_bytes_locked(self) -> None:
        for t in self._hwm_trackers:
            t._note(self.device_bytes)

    def track_high_water(self) -> "HighWaterTracker":
        """Open a device-bytes high-water window (the scheduler's
        estimate-refinement probe: one per running query).  Under
        concurrency the window sees OTHER queries' registered bytes too
        — a conservative over-estimate, which is the safe direction for
        admission control."""
        with self._lock:
            t = HighWaterTracker(self, self.device_bytes)
            self._hwm_trackers.append(t)
            return t

    def _end_high_water(self, t: "HighWaterTracker") -> None:
        with self._lock:
            if t in self._hwm_trackers:
                self._hwm_trackers.remove(t)

    # -- spill logic -------------------------------------------------------
    def _spill_candidates(self) -> List[_Buffer]:
        with self._lock:
            cands = [b for b in self._buffers.values()
                     if b.tier == StorageTier.DEVICE and not b.closed]
        # lowest priority spills first (reference: HashedPriorityQueue order)
        return sorted(cands, key=lambda b: b.priority)

    def _maybe_spill(self) -> None:
        if self.device_bytes <= self.device_budget:
            return
        need = self.device_bytes - self.device_budget
        self.spill_to_fit(need)

    def spill_to_fit(self, bytes_needed: int) -> int:
        """Synchronously spill device buffers until bytes_needed freed
        (DeviceMemoryEventHandler.onAllocFailure analog)."""
        freed = 0
        for buf in self._spill_candidates():
            if freed >= bytes_needed:
                break
            freed += self._spill_one(buf)
        return freed

    def _spill_one(self, buf: _Buffer) -> int:
        with buf.lock:
            if buf.tier != StorageTier.DEVICE or buf.closed:
                return 0
            batch = buf.device_batch
            payload = _device_to_host(batch, self.host_arena)
            buf.host = payload
            buf.device_batch = None
            buf.tier = StorageTier.HOST
            size = buf.size
        with self._lock:
            self.device_bytes -= size
            self.host_bytes += payload.nbytes()
            self.spilled_device_bytes += size
        reg = obsreg.get_registry()
        reg.inc("spill.events")
        reg.inc("spill.deviceToHostBytes", size)
        reg.gauge_max("spill.hostBytesHwm", self.host_bytes)
        obsrec.record_event("spill.deviceToHost", buffer=buf.id,
                            bytes=size, host_bytes=self.host_bytes)
        self._maybe_spill_host()
        return size

    def _maybe_spill_host(self) -> None:
        while self.host_bytes > self.host_budget:
            with self._lock:
                cands = [b for b in self._buffers.values()
                         if b.tier == StorageTier.HOST and not b.closed]
            if not cands:
                return
            victim = min(cands, key=lambda b: b.priority)
            self._spill_to_disk(victim)

    def _spill_to_disk(self, buf: _Buffer) -> None:
        with buf.lock:
            if buf.tier != StorageTier.HOST or buf.closed:
                return
            path = os.path.join(self.spill_dir, f"buf_{buf.id}.npz")
            arrays = {}
            for i, (d, v, l) in enumerate(buf.host.arrays):
                arrays[f"d{i}"] = d
                arrays[f"v{i}"] = v
                if l is not None:
                    arrays[f"l{i}"] = l
            np.savez(path, **arrays)
            nbytes = buf.host.nbytes()
            buf.host.close()
            buf.host = None
            buf.disk_path = path
            buf.tier = StorageTier.DISK
        with self._lock:
            self.host_bytes -= nbytes
            self.spilled_disk_bytes += nbytes
        reg = obsreg.get_registry()
        reg.inc("spill.events")
        reg.inc("spill.hostToDiskBytes", nbytes)
        obsrec.record_event("spill.hostToDisk", buffer=buf.id,
                            bytes=nbytes)

    # -- access ------------------------------------------------------------
    def acquire(self, buffer_id: int) -> DeviceBatch:
        """Materialize the batch on device (unspilling as needed)."""
        buf = self._buffers[buffer_id]
        with buf.lock:
            assert not buf.closed, "buffer already closed"
            if buf.tier == StorageTier.DEVICE:
                return buf.device_batch
            if buf.tier == StorageTier.DISK:
                self._disk_to_host_locked(buf)
            obsreg.get_registry().inc("spill.unspills")
            batch = _host_to_device(buf.host, buf.meta)
            # promote back to device tier
            nbytes = buf.host.nbytes()
            buf.host.close()
            buf.host = None
            buf.device_batch = batch
            buf.tier = StorageTier.DEVICE
        with self._lock:
            self.host_bytes -= nbytes
            self.device_bytes += buf.size
            self._note_device_bytes_locked()
        self._maybe_spill()
        return batch

    def _disk_to_host_locked(self, buf: _Buffer) -> None:
        names, dtypes, num_rows = buf.meta
        loaded = np.load(buf.disk_path)
        arrays = []
        for i, d in enumerate(dtypes):
            arrays.append((loaded[f"d{i}"], loaded[f"v{i}"],
                           loaded[f"l{i}"] if f"l{i}" in loaded else None))
        buf.host = _HostPayload(names, dtypes, num_rows, arrays)
        os.unlink(buf.disk_path)
        buf.disk_path = None
        buf.tier = StorageTier.HOST
        with self._lock:
            self.host_bytes += buf.host.nbytes()

    def tier_of(self, buffer_id: int) -> StorageTier:
        return self._buffers[buffer_id].tier

    def spill_buffer(self, buffer_id: int) -> int:
        """Targeted spill of ONE registered buffer device->host
        (grace-join partitions demote themselves while parked instead
        of waiting for global pressure).  Returns device bytes freed
        (0 when already off-device or closed)."""
        buf = self._buffers.get(buffer_id)
        if buf is None:
            return 0
        return self._spill_one(buf)

    def release(self, buffer_id: int) -> None:
        buf = self._buffers.pop(buffer_id, None)
        if buf is None:
            return
        with buf.lock:
            buf.closed = True
            if buf.tier == StorageTier.DEVICE:
                with self._lock:
                    self.device_bytes -= buf.size
            elif buf.tier == StorageTier.HOST:
                with self._lock:
                    self.host_bytes -= buf.host.nbytes()
                buf.host.close()
            elif buf.disk_path and os.path.exists(buf.disk_path):
                os.unlink(buf.disk_path)
            buf.device_batch = None


class HighWaterTracker:
    """One device-bytes high-water window over the catalog (see
    :meth:`BufferCatalog.track_high_water`)."""

    __slots__ = ("_catalog", "_start", "_peak", "_closed")

    def __init__(self, catalog: "BufferCatalog", start_bytes: int):
        self._catalog = catalog
        self._start = start_bytes
        self._peak = start_bytes
        self._closed = False

    def _note(self, device_bytes: int) -> None:
        if device_bytes > self._peak:
            self._peak = device_bytes

    def peak(self) -> int:
        return self._peak

    def delta(self) -> int:
        """Peak GROWTH over the window (peak - start): what this
        query's run added on top of whatever was already resident
        (cached blobs, other queries' working sets) — the admission
        estimate refines on this, not the absolute catalog peak, so a
        cheap query that merely ran next to a heavyweight one is not
        booked at the neighbour's footprint."""
        return self._peak - self._start

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._catalog._end_high_water(self)


class SpillableBatch:
    """Operator-held handle to a batch that remains spillable
    (SpillableColumnarBatch analog)."""

    def __init__(self, catalog: BufferCatalog, buffer_id: int):
        self._catalog = catalog
        self._id = buffer_id
        self._closed = False

    def get(self) -> DeviceBatch:
        return self._catalog.acquire(self._id)

    @property
    def tier(self) -> StorageTier:
        return self._catalog.tier_of(self._id)

    def spill(self) -> int:
        """Demote this batch off the device tier now (see
        :meth:`BufferCatalog.spill_buffer`)."""
        if self._closed:
            return 0
        return self._catalog.spill_buffer(self._id)

    def close(self) -> None:
        if not self._closed:
            self._catalog.release(self._id)
            self._closed = True

    def __del__(self):
        # abandoned handles (e.g. a limit short-circuiting an adaptive
        # join's readers) must not pin catalog entries forever
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class PlainBatchHandle:
    """SpillableBatch-shaped holder (get/close) used by operators that
    buffer batches when the spill catalog is disabled."""

    def __init__(self, batch: DeviceBatch):
        self._batch = batch

    def get(self) -> DeviceBatch:
        return self._batch

    @property
    def tier(self) -> StorageTier:
        return StorageTier.DEVICE

    def spill(self) -> int:
        return 0  # nowhere to go with the catalog disabled

    def close(self) -> None:
        self._batch = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


def register_or_hold(batch: DeviceBatch,
                     priority: Optional[int] = None):
    """Register `batch` in the global spill catalog when enabled, else
    wrap it in a PlainBatchHandle; either way the caller gets a
    get()/close() handle.  ``priority`` overrides the catalog's
    default spill priority (e.g. INPUT_FROM_SHUFFLE_PRIORITY for
    prepared pipelined-shuffle partitions)."""
    if not is_enabled():
        return PlainBatchHandle(batch)
    if priority is None:
        return get_catalog().register(batch)
    return get_catalog().register(batch, priority=priority)


# ---------------------------------------------------------------------------
# device <-> host payload conversion
# ---------------------------------------------------------------------------

def _device_to_host(batch: DeviceBatch, arena: HostArena) -> _HostPayload:
    arrays = []
    allocations = []
    for c in batch.columns:
        d = np.asarray(c.data)
        v = np.asarray(c.validity)
        l = np.asarray(c.lengths) if c.lengths is not None else None
        # stage through the native arena when a block fits (pinned-pool
        # analog); otherwise keep the plain numpy copy
        alloc = arena.alloc(d.nbytes)
        if alloc is not None:
            staged = alloc.as_numpy(d.dtype, d.shape)
            np.copyto(staged, d)
            d = staged
            allocations.append(alloc)
        arrays.append((d, v, l))
    return _HostPayload(list(batch.names),
                        [c.dtype for c in batch.columns],
                        int(batch.num_rows), arrays, allocations)


def _host_to_device(payload: _HostPayload, meta) -> DeviceBatch:
    names, dtypes, num_rows = meta
    cols = []
    for (d, v, l), dty in zip(payload.arrays, dtypes):
        cols.append(DeviceColumn(
            dty, jnp.asarray(d), jnp.asarray(v),
            jnp.asarray(l) if l is not None else None))
    return DeviceBatch(names, cols, num_rows)


# ---------------------------------------------------------------------------
# process-wide catalog (GpuShuffleEnv-style executor singleton; reference:
# GpuShuffleEnv.scala:26-108, RapidsBufferCatalog.init)
# ---------------------------------------------------------------------------

_GLOBAL: Optional[BufferCatalog] = None
_GLOBAL_ENABLED = True
_GLOBAL_LOCK = threading.Lock()


def init_catalog(device_budget: int, host_budget: int,
                 spill_dir: Optional[str] = None) -> BufferCatalog:
    global _GLOBAL, _GLOBAL_ENABLED
    with _GLOBAL_LOCK:
        _GLOBAL = BufferCatalog(device_budget, host_budget,
                                spill_dir or None)
        _GLOBAL_ENABLED = True
        return _GLOBAL


def disable_catalog() -> None:
    """spark.rapids.tpu.memory.spill.enabled=false: operators hold batches
    directly, nothing is registered or spilled."""
    global _GLOBAL_ENABLED
    with _GLOBAL_LOCK:
        _GLOBAL_ENABLED = False


def is_enabled() -> bool:
    with _GLOBAL_LOCK:
        return _GLOBAL_ENABLED


def hbm_oom_recover(e: BaseException) -> bool:
    """Alloc-failure-driven spill (DeviceMemoryEventHandler.onAllocFailure
    analog, reference: DeviceMemoryEventHandler.scala:42-70).

    XLA owns HBM, so instead of an in-allocator callback the engine
    catches the failed dispatch/read, synchronously spills EVERY
    device-tier registered buffer to host, and tells the caller to
    retry.  Returns True when the error is an HBM exhaustion and bytes
    were actually freed."""
    msg = str(e)
    if "RESOURCE_EXHAUSTED" not in msg and \
            "out of memory" not in msg.lower():
        return False
    cat = get_catalog()
    freed = cat.spill_to_fit(1 << 62)     # evict the whole device tier
    if freed > 0:
        # the flight recorder bundles a SUCCESSFUL query whose window
        # moved this counter — surviving only by evicting the whole
        # device tier is a diagnosis waiting to happen
        obsreg.get_registry().inc("mem.oomRetries")
        obsrec.record_event("mem.oomRetry", freed_bytes=freed,
                            error=msg[:200])
    return freed > 0


# ---------------------------------------------------------------------------
# Auxiliary pressure spillers (in-flight shuffle buffers, etc.)
# ---------------------------------------------------------------------------

_PRESSURE_SPILLERS: List = []   # weakref.ref to objects w/ pressure_spill
_PRESSURE_LOCK = threading.Lock()


def register_pressure_spiller(obj) -> None:
    """Register an object exposing ``pressure_spill(bytes_needed) ->
    bytes_freed`` with the admission-pressure hook.  Held by weakref:
    a shuffle's received-buffer catalog (the main client) registers at
    construction and simply drops out when the exchange releases it —
    no unregister ceremony on the error paths."""
    import weakref
    with _PRESSURE_LOCK:
        _PRESSURE_SPILLERS[:] = [r for r in _PRESSURE_SPILLERS
                                 if r() is not None]
        _PRESSURE_SPILLERS.append(weakref.ref(obj))


def _aux_pressure_spill(bytes_needed: int) -> int:
    freed = 0
    with _PRESSURE_LOCK:
        refs = list(_PRESSURE_SPILLERS)
    for r in refs:
        if freed >= bytes_needed:
            break
        obj = r()
        if obj is None:
            continue
        try:
            freed += int(obj.pressure_spill(bytes_needed - freed))
        except Exception:
            # a broken spiller must not fail admission — but it must
            # be auditable: 0 aux bytes with errors ticking is
            # "spiller broken", not "nothing pending"
            obsreg.get_registry().inc("spill.pressureAuxErrors")
    return freed


def handle_memory_pressure(bytes_needed: int) -> int:
    """Admission-control memory-pressure hook: when the scheduler
    admits a query into the top of the memory budget, proactively
    spill lowest-priority registered device batches so real HBM backs
    the newly admitted estimate (the DeviceMemoryEventHandler role,
    driven from admission instead of an alloc failure).  When the
    device tier alone can't cover it, auxiliary spillers run —
    in-flight received shuffle payloads move host->disk (pipelined
    shuffle buffers respond to pressure instead of stalling
    admission).  Returns bytes freed; a no-op while spill is
    disabled."""
    if not is_enabled() or bytes_needed <= 0:
        return 0
    device_freed = get_catalog().spill_to_fit(int(bytes_needed))
    aux_freed = 0
    if device_freed < bytes_needed:
        aux_freed = _aux_pressure_spill(
            int(bytes_needed) - device_freed)
    # tier-split accounting: device bytes are reclaimed HBM backing;
    # aux bytes are host RAM moved to disk (received shuffle payloads)
    # — capacity tuning must not read the second as the first (the
    # summed return feeds sched.pressureSpillBytes as total relief)
    reg = obsreg.get_registry()
    if device_freed:
        reg.inc("spill.pressureDeviceBytes", device_freed)
    if aux_freed:
        reg.inc("spill.pressureAuxBytes", aux_freed)
    if device_freed or aux_freed:
        reg.inc("spill.pressureSpills")
    return device_freed + aux_freed


def get_catalog() -> BufferCatalog:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = BufferCatalog()
        return _GLOBAL
