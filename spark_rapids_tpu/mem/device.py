"""Device manager + task concurrency gate.

Analogs:
  * ``TpuDeviceManager`` — GpuDeviceManager.initializeGpuAndMemory
    (reference: GpuDeviceManager.scala:31-307): one accelerator per executor,
    memory pool sizing.  On TPU, XLA owns the HBM allocator; our arena
    accounting (mem/spill.py) tracks registered batch bytes on top of it and
    triggers spill when over budget.
  * ``tpu_semaphore`` — GpuSemaphore.acquireIfNecessary
    (reference: GpuSemaphore.scala:27-161): bounds how many tasks
    concurrently build device working sets.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional

from spark_rapids_tpu.obs import registry as obsreg
from spark_rapids_tpu.obs import trace as obstrace
from spark_rapids_tpu.sched import cancel as _cancel
from spark_rapids_tpu.sched.admission import TaskGate

_LOCK = threading.Lock()
_GATE: Optional[TaskGate] = None
_SLOTS = 2


def initialize(concurrent_tasks: int) -> None:
    global _GATE, _SLOTS
    with _LOCK:
        _SLOTS = max(1, int(concurrent_tasks))
        _GATE = TaskGate(_SLOTS)


def _get() -> TaskGate:
    global _GATE
    with _LOCK:
        if _GATE is None:
            _GATE = TaskGate(_SLOTS)
        return _GATE


@contextlib.contextmanager
def tpu_semaphore(metrics=None):
    """Acquire one device-concurrency slot, measuring acquisition count
    and acquire-blocked nanoseconds so concurrency-limit starvation is
    visible per query: process-wide into the metrics registry
    (``semaphore.acquires`` / ``semaphore.waitNs``), per-exec into
    ``metrics.extra`` when the caller passes its Metrics, and as a
    ``semaphore.wait`` span when tracing is on.  Per-acquisition
    bookkeeping cost: a non-blocking acquire, a clock read, and ONE
    registry-lock dict update (plus the caller's Metrics lock when
    passed) — sub-microsecond against the multi-ms device dispatches
    the semaphore gates.

    The slot source is the scheduler's re-entrant
    :class:`~spark_rapids_tpu.sched.admission.TaskGate`: a thread that
    already holds a slot (scan prefetch finishing under an exchange)
    re-enters for FREE — no second slot (which deadlocked at 1 slot)
    and no double-counted blocked-ns; re-entries count into
    ``semaphore.reentries`` instead of ``semaphore.acquires``.  A
    cancelled query raises at the acquire instead of taking (or
    waiting on) a slot."""
    _cancel.check_current()
    gate = _get()
    wait_ns, reentrant = gate.acquire()
    reg = obsreg.get_registry()
    if reentrant:
        reg.inc("semaphore.reentries")
        if metrics is not None:
            metrics.add_extra("semaphore.reentries", 1)
        try:
            yield
        finally:
            gate.release()
        return
    if wait_ns:
        obstrace.record("semaphore.wait",
                        time.perf_counter_ns() - wait_ns, wait_ns,
                        cat="semaphore")
        reg.inc_many(("semaphore.acquires", 1),
                     ("semaphore.waitNs", wait_ns))
    else:
        reg.inc("semaphore.acquires")
    if metrics is not None:
        metrics.add_extra("semaphore.acquires", 1)
        if wait_ns:
            metrics.add_extra("semaphore.waitNs", wait_ns)
    try:
        yield
    finally:
        gate.release()


class TpuDeviceManager:
    """Holds device handles + memory budget (XLA owns the real allocator)."""

    _instance: Optional["TpuDeviceManager"] = None

    def __init__(self, pool_fraction: float = 0.9):
        import jax
        self.devices = jax.devices()
        self.default_device = self.devices[0]
        self.pool_fraction = pool_fraction
        stats = {}
        try:
            stats = self.default_device.memory_stats() or {}
        except Exception:
            pass
        limit = stats.get("bytes_limit")
        self.hbm_budget = int(limit * pool_fraction) if limit else 8 << 30

    @classmethod
    def get(cls) -> "TpuDeviceManager":
        if cls._instance is None:
            cls._instance = TpuDeviceManager()
        return cls._instance

    @property
    def platform(self) -> str:
        return self.default_device.platform
